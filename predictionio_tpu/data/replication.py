"""Replicated event plane: WAL shipping, follower apply, fencing.

PR 6 made the event log durable on one box (segments, digests, cold
tier); this module makes it survive the box. The design is classic
primary-backup WAL shipping, specialized to the PEL on-disk contract:

- **Leader side** (:class:`Replicator`): attached to a
  :class:`~predictionio_tpu.data.filestore.NativeEventLogStore` via
  ``set_replicator``. After every committed append (and inside the
  same per-namespace writer lock, so ordering is exact) it tails the
  ACTIVE segment file from the last replicated byte offset and pushes
  the new bytes to every follower as one **WAL batch**: raw file
  bytes + ``(namespace, segment id, start offset, crc32c, epoch)``.
  Because the payload is the file's own bytes — 8-byte ``PELOGv2``
  header included — a follower that applies every batch holds a
  **byte-identical** copy: same frames, same CRCs, same digests,
  ``pio fsck``-clean by construction. Rollover ships a **seal**
  command carrying the sealed file's sha256; the follower renames its
  copy and refuses a digest mismatch exactly like the cold-tier fetch
  path does.

- **Follower side** (:class:`ReplicaHome`): a pure-Python applier
  over a storage-home-shaped directory (``<home>/eventlog/...``). It
  needs no native engine while following — it appends verified bytes,
  maintains ``segments.json`` manifests compatible with
  :class:`~predictionio_tpu.data.segments.SegMeta`, and persists an
  acked-offset cursor in ``replica_state.json``. On promotion the
  event server simply opens a real store over the same home.

- **Fencing**: every batch carries the leader's **epoch** — its
  fencing token from the shared election lease (the
  ``TrainerLease`` pattern, see ``server/repl_server.py``). A
  follower records the highest epoch it has seen and refuses anything
  older (:class:`StaleEpochError`), so a demoted leader's late pushes
  can never land. Locally, a demoted leader's own appends raise
  :class:`FencedWriteError` before touching the log.

Failure handling is explicit, never silent: a CRC mismatch on a
batch is :class:`WalTornError` (drilled via the
``replication.wal.torn`` byte-flip site), an offset mismatch is
:class:`WalGapError` and the error carries the follower's true cursor
so the leader can resend from it, and sealed segments the push stream
missed (or whose digest moved under tombstone re-seals) are healed by
:meth:`ReplicaHome.sync_sealed` — a digest-verified full-file fetch
riding the same blob+sha discipline as ``LogNamespace.ship``.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from predictionio_tpu.data.pel_integrity import PEL_MAGIC, crc32c
from predictionio_tpu.data.segments import MANIFEST_SCHEMA, SegMeta
from predictionio_tpu.utils import faults, tracing
from predictionio_tpu.utils.atomic_write import atomic_write_text
from predictionio_tpu.utils.metrics import REGISTRY

_U32 = struct.Struct("<I")

#: follower state gauge values (documented in docs/observability.md)
STATE_IDLE, STATE_FOLLOWING, STATE_PROMOTING, STATE_LEADER, STATE_FENCED = (
    0, 1, 2, 3, 4)

REPL_LAG_BYTES = REGISTRY.gauge(
    "pio_repl_lag_bytes",
    "Active-segment bytes appended on the leader but not yet acked by "
    "the follower", ("follower",))
REPL_LAG_RECORDS = REGISTRY.gauge(
    "pio_repl_lag_records",
    "Event records appended on the leader but not yet acked by the "
    "follower", ("follower",))
REPL_EPOCH = REGISTRY.gauge(
    "pio_repl_epoch",
    "This node's current replication fencing epoch (the election "
    "lease token)")
REPL_STATE = REGISTRY.gauge(
    "pio_repl_follower_state",
    "Replication role state: 0 idle, 1 following, 2 promoting, "
    "3 leader, 4 fenced (demoted)")
REPL_BATCHES = REGISTRY.counter(
    "pio_repl_batches_total",
    "WAL batches applied/refused by result (ok, stale_epoch, "
    "crc_refused, gap, error)", ("result",))
REPL_PROMOTIONS = REGISTRY.counter(
    "pio_repl_promotions_total", "Follower promotions to leader")
REPL_SEALS = REGISTRY.counter(
    "pio_repl_seals_total",
    "Sealed-segment transfers applied on the follower by result",
    ("result",))


class ReplicationError(RuntimeError):
    """Base class for replication protocol failures."""


class StaleEpochError(ReplicationError):
    """A write carried a fencing epoch older than one already seen —
    a demoted leader is trying to land a late write. Always refused."""


class WalTornError(ReplicationError):
    """A WAL batch failed its CRC — torn or corrupted in flight. The
    follower's log is untouched; the leader must resend."""


class WalGapError(ReplicationError):
    """A WAL batch does not start where the follower's log ends.
    Carries the follower's true cursor so the leader can resend."""

    def __init__(self, message: str, seg_id: int, offset: int) -> None:
        super().__init__(message)
        self.seg_id = seg_id
        self.offset = offset


class FencedWriteError(ReplicationError):
    """A local append was attempted on a node whose leadership was
    lost. Raised BEFORE bytes touch the log — a demoted leader can
    never corrupt the log it no longer owns."""


# -- WAL batch ----------------------------------------------------------------


@dataclass
class WalBatch:
    """One replicated chunk of an active segment file."""

    ns_tag: str            # e.g. "events_1" / "events_1_2" / "events_1.s1"
    seg_id: int            # the id this ACTIVE file will get when sealed
    offset: int            # byte offset the payload starts at
    payload: bytes         # raw file bytes (offset 0 includes the header)
    crc: int               # crc32c over payload
    epoch: int             # leader's fencing token
    records: int = 0       # complete frames in the payload (lag metric)

    @classmethod
    def build(cls, ns_tag: str, seg_id: int, offset: int, payload: bytes,
              epoch: int) -> "WalBatch":
        return cls(ns_tag=ns_tag, seg_id=seg_id, offset=offset,
                   payload=payload, crc=crc32c(payload), epoch=epoch,
                   records=count_frames(payload, offset == 0))


def count_frames(payload: bytes, file_start: bool, version: int = 2) -> int:
    """Number of complete PEL frames in ``payload``. ``file_start``
    skips the 8-byte magic header. Counts only — the byte-level CRC of
    each frame is the follower's fsck's job, not the wire protocol's
    (the batch has its own CRC)."""
    off = len(PEL_MAGIC) if file_start else 0
    trailer = 4 if version == 2 else 0
    n = 0
    size = len(payload)
    while off + 5 <= size:
        rec_len = _U32.unpack_from(payload, off)[0]
        if rec_len < 1 or off + 4 + rec_len + trailer > size:
            break
        off += 4 + rec_len + trailer
        n += 1
    return n


# -- follower: the replica home -----------------------------------------------

REPLICA_STATE_NAME = "replica_state.json"


class ReplicaHome:
    """Byte-level applier over a storage-home-shaped directory.

    Not a store: while following, nothing opens the files through the
    native engine — this class appends verified bytes and keeps the
    manifests that a real :class:`NativeEventLogStore` will read the
    moment the node is promoted. All mutation is serialized by one
    lock (follower apply is single-streamed by design: the leader
    pushes in commit order)."""

    def __init__(self, root: str) -> None:
        self.root = root
        self.log_dir = os.path.join(root, "eventlog")
        os.makedirs(self.log_dir, exist_ok=True)
        self.lock = threading.Lock()
        self.epoch = 0
        #: ns_tag -> {"seg": active seg id, "offset": bytes applied}
        self.cursors: Dict[str, Dict[str, int]] = {}
        self._load_state()

    # -- persisted state ---------------------------------------------------

    @property
    def state_path(self) -> str:
        return os.path.join(self.root, REPLICA_STATE_NAME)

    def _load_state(self) -> None:
        try:
            with open(self.state_path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return
        self.epoch = int(doc.get("epoch", 0))
        self.cursors = {str(k): {"seg": int(v["seg"]),
                                 "offset": int(v["offset"])}
                        for k, v in doc.get("cursors", {}).items()}

    def _save_state(self) -> None:
        atomic_write_text(self.state_path, json.dumps(
            {"epoch": self.epoch, "cursors": self.cursors},
            indent=1, sort_keys=True))

    # -- paths -------------------------------------------------------------

    def active_path(self, ns_tag: str) -> str:
        return os.path.join(self.log_dir, ns_tag + ".pel")

    def seg_dir(self, ns_tag: str) -> str:
        return os.path.join(self.log_dir, ns_tag + ".peld")

    def manifest_path(self, ns_tag: str) -> str:
        return os.path.join(self.seg_dir(ns_tag), "segments.json")

    def _load_manifest(self, ns_tag: str) -> Dict[str, Any]:
        try:
            with open(self.manifest_path(ns_tag), "r",
                      encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"schema": MANIFEST_SCHEMA, "next_id": 0, "segments": []}

    def _write_manifest(self, ns_tag: str, doc: Dict[str, Any]) -> None:
        os.makedirs(self.seg_dir(ns_tag), exist_ok=True)
        atomic_write_text(self.manifest_path(ns_tag),
                          json.dumps(doc, indent=1, sort_keys=True))

    # -- epoch fencing -----------------------------------------------------

    def check_epoch(self, epoch: int) -> None:
        """Refuse anything older than the highest epoch seen; learn a
        newer one. Must hold ``lock``."""
        if epoch < self.epoch:
            REPL_BATCHES.inc(("stale_epoch",))
            raise StaleEpochError(
                f"write carries epoch {epoch} but this replica has "
                f"seen epoch {self.epoch} — refusing a demoted "
                "leader's late write")
        if epoch > self.epoch:
            self.epoch = epoch
            REPL_EPOCH.set(epoch)

    # -- WAL apply ---------------------------------------------------------

    def cursor(self, ns_tag: str) -> Tuple[int, int]:
        """(active seg id, applied byte offset) for one namespace."""
        cur = self.cursors.get(ns_tag)
        if cur is None:
            return 0, 0
        return cur["seg"], cur["offset"]

    def apply_wal(self, batch: WalBatch) -> int:
        """Verify and append one WAL batch; returns the new applied
        offset. The follower-lag drill site lives here: an armed
        ``replication.follower.lag`` latency plan slows every apply,
        which the leader sees as ack latency → lag."""
        faults.inject("replication.follower.lag")
        with self.lock:
            self.check_epoch(batch.epoch)
            payload = faults.corrupt_bytes("replication.wal.torn",
                                           batch.payload)
            if crc32c(payload) != batch.crc:
                REPL_BATCHES.inc(("crc_refused",))
                raise WalTornError(
                    f"WAL batch for {batch.ns_tag} @ {batch.offset} "
                    "failed crc32c — refusing torn frame")
            seg, off = self.cursor(batch.ns_tag)
            path = self.active_path(batch.ns_tag)
            have = os.path.getsize(path) if os.path.exists(path) else 0
            # the authoritative offset is the FILE, not the cursor doc
            # (a crash between append and state write leaves the file
            # ahead by exactly one acked batch — trust the bytes)
            off = max(off, have) if seg == batch.seg_id else off
            if batch.seg_id != seg or batch.offset != off:
                REPL_BATCHES.inc(("gap",))
                raise WalGapError(
                    f"WAL batch for {batch.ns_tag} starts at "
                    f"seg {batch.seg_id}/{batch.offset} but replica is "
                    f"at seg {seg}/{off}", seg, off)
            if off == 0 and not payload.startswith(PEL_MAGIC):
                REPL_BATCHES.inc(("error",))
                raise ReplicationError(
                    f"first batch for {batch.ns_tag} does not begin "
                    "with the PELOGv2 header")
            with open(path, "ab") as f:
                f.write(payload)
                f.flush()
                # follower apply is single-streamed (the leader pushes
                # serially, in commit order) — no other writer exists
                # to stall behind this sync, and the ack contract
                # requires it inside the cursor update
                os.fsync(f.fileno())  # pio-lint: disable=PL03
            new_off = off + len(payload)
            self.cursors[batch.ns_tag] = {"seg": batch.seg_id,
                                          "offset": new_off}
            self._save_state()
            REPL_BATCHES.inc(("ok",))
            return new_off

    def apply_seal(self, ns_tag: str, seg_meta: Dict[str, Any],
                   epoch: int) -> None:
        """The leader sealed its active segment: rename our copy into
        the ``.peld`` dir, verify the byte-identity claim against the
        leader's digest, and record the manifest row. A digest mismatch
        refuses the seal and leaves the file in place for resync."""
        meta = SegMeta.from_dict(seg_meta)
        with self.lock:
            self.check_epoch(epoch)
            src = self.active_path(ns_tag)
            if not os.path.exists(src):
                REPL_SEALS.inc(("error",))
                raise ReplicationError(
                    f"seal for {ns_tag}/{meta.file} but replica has no "
                    "active file — resync needed")
            if meta.sha256 is not None:
                actual = _file_sha256(src)
                if actual != meta.sha256:
                    REPL_SEALS.inc(("digest_mismatch",))
                    raise ReplicationError(
                        f"sealed segment {ns_tag}/{meta.file} digest "
                        f"mismatch (leader {meta.sha256[:12]}…, replica "
                        f"{actual[:12]}…) — replica diverged, resync "
                        "needed")
            os.makedirs(self.seg_dir(ns_tag), exist_ok=True)
            os.rename(src, os.path.join(self.seg_dir(ns_tag), meta.file))
            doc = self._load_manifest(ns_tag)
            rows = [d for d in doc["segments"]
                    if int(d.get("id", -1)) != meta.id]
            row = meta.to_dict()
            # local-cache sidecars (columnar, id filter) do not ship
            # over the WAL stream; the promoted store rebuilds them
            row["cols"] = None
            row["idf"] = None
            rows.append(row)
            rows.sort(key=lambda d: int(d["id"]))
            doc["segments"] = rows
            doc["next_id"] = max(int(doc.get("next_id", 0)), meta.id + 1)
            self._write_manifest(ns_tag, doc)
            cur = self.cursors.setdefault(ns_tag, {"seg": 0, "offset": 0})
            cur["seg"] = meta.id + 1
            cur["offset"] = 0
            self._save_state()
            REPL_SEALS.inc(("ok",))

    # -- sealed-segment catch-up ------------------------------------------

    def sync_sealed(self, ns_tag: str, manifest: Dict[str, Any],
                    fetch: Callable[[str, str], Optional[bytes]],
                    epoch: int) -> int:
        """Heal sealed segments the push stream missed: for every row
        in the leader's ``manifest`` whose frame file we lack (or whose
        digest moved — tombstone re-seals), fetch the blob, verify its
        sha256, and install it. ``fetch(ns_tag, file)`` returns the
        blob or None (cold segments have no local frame file on the
        leader either; their manifest row is copied as-is and the
        cold-tier digest check applies on any later fetch). Returns
        the number of files installed."""
        installed = 0
        with self.lock:
            self.check_epoch(epoch)
            doc = self._load_manifest(ns_tag)
            rows = {int(d["id"]): d for d in doc["segments"]}
            for d in manifest.get("segments", []):
                meta = SegMeta.from_dict(d)
                path = os.path.join(self.seg_dir(ns_tag), meta.file)
                have = rows.get(meta.id)
                digest_ok = (os.path.exists(path) and meta.sha256
                             and _file_sha256(path) == meta.sha256)
                if have and digest_ok:
                    continue
                if meta.state != "cold":
                    blob = fetch(ns_tag, meta.file)
                    if blob is None:
                        REPL_SEALS.inc(("error",))
                        continue
                    blob = faults.corrupt_bytes("replication.wal.torn",
                                                blob)
                    if meta.sha256 and _sha256(blob) != meta.sha256:
                        REPL_SEALS.inc(("digest_mismatch",))
                        raise ReplicationError(
                            f"fetched segment {ns_tag}/{meta.file} "
                            "failed digest verification — refusing it")
                    os.makedirs(self.seg_dir(ns_tag), exist_ok=True)
                    tmp = path + ".part"
                    with open(tmp, "wb") as f:
                        f.write(blob)
                        f.flush()
                        # catch-up runs on the follower's watch
                        # thread; the apply stream shares this lock by
                        # design (sealed installs must serialize with
                        # WAL appends), so there is no writer to stall
                        os.fsync(f.fileno())  # pio-lint: disable=PL03
                    os.rename(tmp, path)
                    installed += 1
                row = meta.to_dict()
                row["cols"] = None
                row["idf"] = None
                rows[meta.id] = row
                REPL_SEALS.inc(("ok",))
            doc["segments"] = sorted(rows.values(),
                                     key=lambda r: int(r["id"]))
            doc["next_id"] = max(
                [int(manifest.get("next_id", 0)),
                 int(doc.get("next_id", 0))]
                + [int(r["id"]) + 1 for r in doc["segments"]])
            self._write_manifest(ns_tag, doc)
            cur = self.cursors.setdefault(ns_tag, {"seg": 0, "offset": 0})
            if cur["seg"] < int(doc["next_id"]):
                # sealed rows beyond our cursor: the active stream
                # restarts at the leader's current active segment
                cur["seg"] = int(doc["next_id"])
                cur["offset"] = 0
            self._save_state()
        return installed

    def status(self) -> Dict[str, Any]:
        with self.lock:
            return {"epoch": self.epoch,
                    "cursors": {k: dict(v)
                                for k, v in sorted(self.cursors.items())}}


def _sha256(blob: bytes) -> str:
    import hashlib

    return hashlib.sha256(blob).hexdigest()


def _file_sha256(path: str) -> str:
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


# -- leader: the replicator ----------------------------------------------------


class FollowerLink:
    """Leader's view of one follower: transport + replication cursor.

    The transport is injectable (``apply_fn``/``seal_fn`` — HTTP in
    production via :class:`~predictionio_tpu.server.repl_server.`
    ``FollowerClient``, in-process in tests). A :class:`WalGapError`
    raised by the transport resets the cursor to the follower's true
    position so the next push resends from there."""

    def __init__(self, name: str,
                 apply_fn: Callable[[WalBatch], int],
                 seal_fn: Callable[[str, Dict[str, Any], int], None],
                 status_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 ) -> None:
        self.name = name
        self.apply_fn = apply_fn
        self.seal_fn = seal_fn
        self.status_fn = status_fn
        #: ns_tag -> [seg_id, offset] acked by this follower
        self.cursors: Dict[str, List[int]] = {}
        self.healthy = True
        self.last_error: Optional[str] = None
        self.probe_countdown = 0


class Replicator:
    """Leader-side push replication, attached to the native store.

    ``on_append(ns)`` runs under the namespace writer lock right after
    a committed append: it reads the active file's new bytes and
    pushes them to every follower, waiting for acks — an acked client
    write therefore implies the bytes are fsynced on every healthy
    follower (semi-synchronous replication; a follower that errors is
    marked unhealthy and skipped until it resyncs, so one dead
    follower degrades durability, never availability)."""

    def __init__(self, followers: List[FollowerLink],
                 epoch: Callable[[], int],
                 fenced: Callable[[], bool] = lambda: False,
                 max_batch_bytes: int = 4 << 20) -> None:
        self.followers = followers
        self._epoch = epoch
        self._fenced = fenced
        self.max_batch_bytes = max_batch_bytes

    # -- fencing (local) ---------------------------------------------------

    def check_fenced(self) -> None:
        if self._fenced():
            raise FencedWriteError(
                "this node's event-plane leadership was lost "
                f"(epoch {self._epoch()}) — writes are fenced; retry "
                "against the new leader")

    # -- hooks (called by NativeEventLogStore under ns.lock) ---------------

    def on_append(self, ns) -> None:
        """Push everything between each follower's cursor and the
        active file's current end."""
        tag = ns.namespace_tag()
        path = ns.base_path
        try:
            size = os.path.getsize(path)
        except OSError:
            return
        with open(path, "rb") as f:
            for link in self.followers:
                if not link.healthy and not self._probe(link, tag,
                                                        ns.next_id):
                    continue
                self._push_range(link, tag, ns.next_id, f, size)

    def _probe(self, link: FollowerLink, tag: str, seg_id: int) -> bool:
        """Try to revive an unhealthy link: every 64th append, ask the
        follower where it actually is (it may have healed itself via
        :meth:`ReplicaHome.sync_sealed`). Revives the link when the
        follower's cursor is back on the current active segment."""
        link.probe_countdown -= 1
        if link.probe_countdown > 0 or link.status_fn is None:
            return False
        link.probe_countdown = 64
        try:
            doc = link.status_fn()
        except Exception as e:  # noqa: BLE001
            link.last_error = f"{type(e).__name__}: {e}"
            return False
        cur = doc.get("cursors", {}).get(tag)
        if cur is None and not doc.get("cursors"):
            # a blank follower starts wherever we start it
            link.cursors[tag] = [seg_id, 0]
            link.healthy = True
            return True
        if cur is not None and int(cur.get("seg", -1)) == seg_id:
            link.cursors[tag] = [seg_id, int(cur.get("offset", 0))]
            link.healthy = True
            return True
        return False

    def _push_range(self, link: FollowerLink, tag: str, seg_id: int,
                    f, size: int) -> None:
        cur = link.cursors.setdefault(tag, [seg_id, 0])
        if cur[0] != seg_id:
            # follower is on an older active file than we think —
            # a seal push must have failed; mark for resync
            link.healthy = False
            link.last_error = (f"cursor on seg {cur[0]} but active is "
                               f"seg {seg_id}")
            return
        while cur[1] < size:
            f.seek(cur[1])
            payload = f.read(min(size - cur[1], self.max_batch_bytes))
            if not payload:
                break
            batch = WalBatch.build(tag, seg_id, cur[1], payload,
                                   self._epoch())
            try:
                with tracing.span("repl.push", follower=link.name,
                                  ns=tag, bytes=len(payload)):
                    acked = link.apply_fn(batch)
                cur[1] = acked
                link.last_error = None
            except WalGapError as e:
                if e.seg_id != seg_id:
                    link.healthy = False
                    link.last_error = str(e)
                    break
                cur[1] = e.offset        # resend from the true cursor
            except StaleEpochError as e:
                link.healthy = False
                link.last_error = str(e)
                break
            except Exception as e:  # noqa: BLE001 — degrade, don't block
                link.healthy = False
                link.last_error = f"{type(e).__name__}: {e}"
                break
            self._lag(link, tag, size, cur[1])
        self._lag(link, tag, size, cur[1], f)

    def _lag(self, link: FollowerLink, tag: str, size: int,
             acked: int, f=None) -> None:
        lag = max(0, size - acked)
        REPL_LAG_BYTES.set(lag, (link.name,))
        if lag == 0:
            REPL_LAG_RECORDS.set(0, (link.name,))
        elif f is not None:
            f.seek(acked)
            rem = f.read(lag)
            REPL_LAG_RECORDS.set(count_frames(rem, acked == 0),
                                 (link.name,))

    def on_seal(self, ns, seg) -> None:
        """The active segment just rolled: finalize its digest (the
        follower verifies byte identity against it) and push the seal.
        Cursors move to (new active seg id, 0)."""
        ns.finalize(seg)
        tag = ns.namespace_tag()
        meta = seg.meta.to_dict()
        for link in self.followers:
            if not link.healthy:
                continue
            cur = link.cursors.setdefault(tag, [seg.meta.id, 0])
            try:
                # drain any unpushed tail of the sealed file first
                sealed_path = ns.seg_path(seg)
                with open(sealed_path, "rb") as f:
                    size = os.path.getsize(sealed_path)
                    self._push_range(link, tag, seg.meta.id, f, size)
                if not link.healthy:
                    continue
                link.seal_fn(tag, meta, self._epoch())
                cur[0] = seg.meta.id + 1
                cur[1] = 0
            except Exception as e:  # noqa: BLE001
                link.healthy = False
                link.last_error = f"{type(e).__name__}: {e}"

    def status(self) -> Dict[str, Any]:
        return {"followers": [
            {"name": l.name, "healthy": l.healthy,
             "lastError": l.last_error,
             "cursors": {k: list(v) for k, v in sorted(l.cursors.items())}}
            for l in self.followers]}


# -- read fan-out --------------------------------------------------------------


def select_read_home(read_from: str, leader_home: str,
                     replica_home: Optional[str] = None) -> str:
    """Resolve ``--read-from follower|leader|any`` to a storage home.

    ``follower`` requires a replica home (``--replica-home`` or
    ``PIO_REPL_REPLICA_HOME``) holding a replicated event log;
    ``any`` prefers the replica when it exists (training reads then
    never contend with the leader's ingest fsyncs) and falls back to
    the leader's home; ``leader`` is the default passthrough."""
    replica_home = replica_home or os.environ.get("PIO_REPL_REPLICA_HOME")
    if read_from == "leader":
        return leader_home
    has_replica = bool(replica_home) and os.path.isdir(
        os.path.join(replica_home, "eventlog"))
    if read_from == "follower":
        if not has_replica:
            raise ValueError(
                "--read-from follower needs a replica home with a "
                "replicated event log (set --replica-home or "
                "PIO_REPL_REPLICA_HOME)")
        return replica_home  # type: ignore[return-value]
    if read_from == "any":
        return replica_home if has_replica else leader_home
    raise ValueError(f"unknown --read-from {read_from!r} "
                     "(want follower|leader|any)")
