"""Event model contract tests (mirrors the reference's event-JSON
round-trip + DataMapSpec coverage, SURVEY.md §4 Tier 1)."""

import datetime as dt

import pytest

from predictionio_tpu.data.event import (
    Event,
    EventValidationError,
    aggregate_properties,
    format_event_time,
    parse_event_time,
    validate_event,
)


def _t(s):
    return parse_event_time(s)


class TestWireFormat:
    def test_round_trip(self):
        obj = {
            "event": "rate",
            "entityType": "user",
            "entityId": "u1",
            "targetEntityType": "item",
            "targetEntityId": "i9",
            "properties": {"rating": 4.5},
            "eventTime": "2026-01-02T03:04:05.678+00:00",
            "tags": ["a", "b"],
            "prId": "pr-1",
        }
        ev = Event.from_json(obj)
        out = ev.with_id().to_json()
        assert out["event"] == "rate"
        assert out["entityType"] == "user"
        assert out["targetEntityId"] == "i9"
        assert out["properties"] == {"rating": 4.5}
        assert out["eventTime"] == "2026-01-02T03:04:05.678+00:00"
        assert out["tags"] == ["a", "b"]
        assert out["prId"] == "pr-1"
        assert out["eventId"]

    def test_z_suffix_and_offsets(self):
        assert _t("2026-01-01T00:00:00Z") == _t("2026-01-01T00:00:00+00:00")
        assert _t("2026-01-01T08:00:00+08:00") == _t("2026-01-01T00:00:00Z")

    def test_naive_time_is_utc(self):
        assert _t("2026-01-01T00:00:00").tzinfo is not None

    def test_missing_required(self):
        with pytest.raises(EventValidationError):
            Event.from_json({"event": "x", "entityType": "user"})

    def test_unknown_field_rejected(self):
        with pytest.raises(EventValidationError):
            Event.from_json({"event": "x", "entityType": "u", "entityId": "1",
                             "bogus": 1})

    def test_format_millis(self):
        t = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
        assert format_event_time(t) == "2026-01-01T00:00:00.000+00:00"


class TestValidation:
    def test_reserved_prefix(self):
        with pytest.raises(EventValidationError):
            validate_event(Event(event="$foo", entity_type="user", entity_id="1"))

    def test_set_with_target_rejected(self):
        with pytest.raises(EventValidationError):
            validate_event(Event(event="$set", entity_type="user", entity_id="1",
                                 target_entity_type="item", target_entity_id="2"))

    def test_unset_requires_properties(self):
        with pytest.raises(EventValidationError):
            validate_event(Event(event="$unset", entity_type="user", entity_id="1"))

    def test_delete_no_properties(self):
        with pytest.raises(EventValidationError):
            validate_event(Event(event="$delete", entity_type="user", entity_id="1",
                                 properties={"a": 1}))

    def test_half_target_rejected(self):
        with pytest.raises(EventValidationError):
            validate_event(Event(event="buy", entity_type="user", entity_id="1",
                                 target_entity_type="item"))

    def test_plain_ok(self):
        validate_event(Event(event="view", entity_type="user", entity_id="1",
                             target_entity_type="item", target_entity_id="2"))


class TestAggregation:
    def test_set_unset_delete_fold(self):
        evs = [
            Event(event="$set", entity_type="user", entity_id="u1",
                  properties={"a": 1, "b": 2}, event_time=_t("2026-01-01T00:00:00Z")),
            Event(event="$set", entity_type="user", entity_id="u1",
                  properties={"b": 3, "c": 4}, event_time=_t("2026-01-02T00:00:00Z")),
            Event(event="$unset", entity_type="user", entity_id="u1",
                  properties={"a": None}, event_time=_t("2026-01-03T00:00:00Z")),
            Event(event="$set", entity_type="user", entity_id="u2",
                  properties={"x": 1}, event_time=_t("2026-01-01T00:00:00Z")),
            Event(event="$delete", entity_type="user", entity_id="u3",
                  event_time=_t("2026-01-05T00:00:00Z")),
            Event(event="$set", entity_type="user", entity_id="u3",
                  properties={"gone": True}, event_time=_t("2026-01-04T00:00:00Z")),
        ]
        snap = aggregate_properties(evs)
        assert snap["u1"].properties == {"b": 3, "c": 4}
        assert snap["u1"].first_updated == _t("2026-01-01T00:00:00Z")
        assert snap["u1"].last_updated == _t("2026-01-03T00:00:00Z")
        assert snap["u2"].properties == {"x": 1}
        assert "u3" not in snap  # $delete after $set (by eventTime) removes it

    def test_fold_is_by_event_time_not_arrival(self):
        evs = [
            Event(event="$set", entity_type="user", entity_id="u",
                  properties={"v": "late"}, event_time=_t("2026-01-02T00:00:00Z")),
            Event(event="$set", entity_type="user", entity_id="u",
                  properties={"v": "early"}, event_time=_t("2026-01-01T00:00:00Z")),
        ]
        assert aggregate_properties(evs)["u"].properties == {"v": "late"}

    def test_non_special_ignored(self):
        evs = [Event(event="view", entity_type="user", entity_id="u")]
        assert aggregate_properties(evs) == {}
