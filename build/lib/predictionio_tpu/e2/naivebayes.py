"""Categorical Naive Bayes over string-valued features.

Reference: [U] e2/.../engine/CategoricalNaiveBayes.scala (unverified,
SURVEY.md §2a) — trains from ``LabeledPoint(label, features:
Array[String])`` where feature *position* is the variable and the string
is its category; the model exposes per-label priors and per-(position,
value) likelihoods, a ``logScore`` with a pluggable default for unseen
values, and ``predict`` = argmax label.

TPU mapping: after host-side vocabulary indexing (BiMap per position),
the count aggregation — one (n, C) one-hot ``Yᵀ`` against a per-position
(n, Vp) one-hot — is a batched MXU matmul, the same shape of compute as
:mod:`predictionio_tpu.models.naive_bayes` but per feature position.
Vocabularies are small; scoring stays host-side numpy for O(µs) serving.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.utils.bimap import BiMap


@dataclass
class LabeledPoint:
    """A training example: string label + positional string features."""

    label: str
    features: Sequence[str]


@dataclass
class CategoricalNaiveBayesModel:
    """priors[label] = log P(label); likelihoods[label][pos][value] =
    log P(value at pos | label)."""

    priors: Dict[str, float]
    likelihoods: Dict[str, List[Dict[str, float]]]
    #: per-position smoothing floor used for values never seen with a label
    min_log_likelihood: Dict[str, List[float]] = field(default_factory=dict)

    def log_score(
        self,
        point: LabeledPoint,
        default_likelihood: Optional[Callable[[List[float]], float]] = None,
    ) -> Optional[float]:
        """Log joint score of ``point`` under its label, or None if the
        label is unknown. ``default_likelihood`` maps the position's
        known log-likelihood values to a score for an unseen value
        (reference default: -inf → None propagation; ours returns the
        smoothed floor unless overridden)."""
        if point.label not in self.priors:
            return None
        pos_tables = self.likelihoods[point.label]
        total = self.priors[point.label]
        for pos, value in enumerate(point.features):
            table = pos_tables[pos]
            if value in table:
                total += table[value]
            elif default_likelihood is not None:
                total += default_likelihood(list(table.values()))
            else:
                total += self.min_log_likelihood[point.label][pos]
        return total

    def predict(self, features: Sequence[str]) -> str:
        """argmax over labels of log_score (reference: predict)."""
        best_label, best = "", -math.inf
        for label in self.priors:
            score = self.log_score(LabeledPoint(label, features))
            if score is not None and score > best:
                best_label, best = label, score
        return best_label


def categorical_naive_bayes_train(
    points: Sequence[LabeledPoint], smoothing: float = 1.0,
) -> CategoricalNaiveBayesModel:
    """Count-and-normalize with additive smoothing.

    The per-position count matrices are computed as one-hot matmuls on
    the accelerator (MXU-friendly); tables are then pulled host-side
    into dicts for serving.
    """
    if not points:
        raise ValueError("categorical_naive_bayes_train: no training points")
    n_pos = len(points[0].features)
    for p in points:
        if len(p.features) != n_pos:
            raise ValueError("all points must have the same number of features")

    labels = BiMap.string_int(sorted({p.label for p in points}))
    pos_vocabs = [
        BiMap.string_int(sorted({p.features[i] for p in points}))
        for i in range(n_pos)
    ]
    y = np.asarray([labels[p.label] for p in points], np.int32)
    C = len(labels.keys())

    import jax
    import jax.numpy as jnp

    Y = jax.nn.one_hot(jnp.asarray(y), C, dtype=jnp.float32)  # (n, C)
    label_counts = np.asarray(Y.sum(axis=0))                   # (C,)

    count_mats: List[np.ndarray] = []
    for i, vocab in enumerate(pos_vocabs):
        xi = np.asarray([vocab[p.features[i]] for p in points], np.int32)
        Xi = jax.nn.one_hot(jnp.asarray(xi), len(vocab.keys()),
                            dtype=jnp.float32)                 # (n, Vp)
        count_mats.append(np.asarray(Y.T @ Xi))                # (C, Vp) matmul

    n = float(len(points))
    priors = {lab: math.log(label_counts[idx] / n)
              for lab, idx in labels.to_dict().items()}
    likelihoods: Dict[str, List[Dict[str, float]]] = {}
    floors: Dict[str, List[float]] = {}
    for lab, ci in labels.to_dict().items():
        tables, lab_floors = [], []
        for i, vocab in enumerate(pos_vocabs):
            Vp = len(vocab.keys())
            denom = label_counts[ci] + smoothing * Vp
            table = {
                val: math.log((count_mats[i][ci, vi] + smoothing) / denom)
                for val, vi in vocab.to_dict().items()
            }
            tables.append(table)
            lab_floors.append(math.log(smoothing / denom))
        likelihoods[lab] = tables
        floors[lab] = lab_floors
    return CategoricalNaiveBayesModel(priors, likelihoods, floors)
