"""Meta-data store: apps, access keys, channels, engine & evaluation instances.

Equivalent of the reference's meta repos (reference: [U] data/.../storage/
{Apps,AccessKeys,Channels,EngineInstances,EvaluationInstances}.scala —
unverified, SURVEY.md §2a), collapsed onto a single SQLite database. The
record shapes mirror the reference's case classes so the CLI verbs
(``pio app new``, ``pio accesskey list``, …) and the servers behave
identically; ``spark_conf`` in the reference's ``EngineInstance`` becomes
``mesh_conf`` (the pjit mesh / compile options used for the run).
"""

from __future__ import annotations

import datetime as _dt
import json
import secrets
import sqlite3
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from predictionio_tpu.data.event import format_event_time, parse_event_time, utcnow


@dataclass
class App:
    id: int
    name: str
    description: str = ""


@dataclass
class AccessKey:
    key: str
    app_id: int
    events: List[str] = field(default_factory=list)  # empty = all events permitted


@dataclass
class Channel:
    id: int
    name: str
    app_id: int


@dataclass
class EngineInstance:
    """One train run's record; serving loads the latest COMPLETED one."""

    id: str
    status: str  # INIT | TRAINING | COMPLETED | FAILED
    start_time: _dt.datetime
    end_time: Optional[_dt.datetime]
    engine_factory: str  # "module.path:factory_callable"
    engine_variant: str
    batch: str
    env: Dict[str, str]
    mesh_conf: Dict[str, Any]
    data_source_params: str
    preparator_params: str
    algorithms_params: str
    serving_params: str


@dataclass
class EvaluationInstance:
    id: str
    status: str
    start_time: _dt.datetime
    end_time: Optional[_dt.datetime]
    evaluation_class: str
    engine_params_generator_class: str
    batch: str
    env: Dict[str, str]
    evaluator_results: str = ""        # human-readable summary
    evaluator_results_html: str = ""
    evaluator_results_json: str = ""   # structured per-candidate scores


_SCHEMA = """
CREATE TABLE IF NOT EXISTS apps (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT UNIQUE NOT NULL,
    description TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS access_keys (
    key TEXT PRIMARY KEY,
    appid INTEGER NOT NULL,
    events TEXT NOT NULL DEFAULT '[]'
);
CREATE TABLE IF NOT EXISTS channels (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT NOT NULL,
    appid INTEGER NOT NULL,
    UNIQUE(name, appid)
);
CREATE TABLE IF NOT EXISTS engine_instances (
    id TEXT PRIMARY KEY,
    status TEXT NOT NULL,
    startTime TEXT NOT NULL,
    endTime TEXT,
    engineFactory TEXT NOT NULL,
    engineVariant TEXT NOT NULL DEFAULT '',
    batch TEXT NOT NULL DEFAULT '',
    env TEXT NOT NULL DEFAULT '{}',
    meshConf TEXT NOT NULL DEFAULT '{}',
    dataSourceParams TEXT NOT NULL DEFAULT '{}',
    preparatorParams TEXT NOT NULL DEFAULT '{}',
    algorithmsParams TEXT NOT NULL DEFAULT '[]',
    servingParams TEXT NOT NULL DEFAULT '{}'
);
CREATE TABLE IF NOT EXISTS evaluation_instances (
    id TEXT PRIMARY KEY,
    status TEXT NOT NULL,
    startTime TEXT NOT NULL,
    endTime TEXT,
    evaluationClass TEXT NOT NULL,
    engineParamsGeneratorClass TEXT NOT NULL DEFAULT '',
    batch TEXT NOT NULL DEFAULT '',
    env TEXT NOT NULL DEFAULT '{}',
    evaluatorResults TEXT NOT NULL DEFAULT '',
    evaluatorResultsHTML TEXT NOT NULL DEFAULT '',
    evaluatorResultsJSON TEXT NOT NULL DEFAULT ''
);
"""


class MetaStore:
    """SQLite-backed meta store (also supports ':memory:' for tests)."""

    def __init__(self, path: str = ":memory:") -> None:
        self._path = path
        self._lock = threading.RLock()
        # ':memory:' must share one connection; files get per-thread conns.
        self._memory_conn: Optional[sqlite3.Connection] = None
        self._local = threading.local()
        if path == ":memory:":
            self._memory_conn = sqlite3.connect(path, check_same_thread=False)
        self._init_schema()

    def _conn(self) -> sqlite3.Connection:
        if self._memory_conn is not None:
            return self._memory_conn
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self._path, timeout=30.0)
            conn.execute("PRAGMA journal_mode=WAL")
            self._local.conn = conn
        return conn

    def _init_schema(self) -> None:
        with self._lock:
            self._conn().executescript(_SCHEMA)
            self._conn().commit()

    # -- apps ------------------------------------------------------------------

    def create_app(self, name: str, description: str = "") -> App:
        with self._lock:
            c = self._conn()
            cur = c.execute(
                "INSERT INTO apps(name, description) VALUES (?,?)", (name, description)
            )
            c.commit()
            assert cur.lastrowid is not None
            return App(id=cur.lastrowid, name=name, description=description)

    def get_app(self, app_id: int) -> Optional[App]:
        row = self._conn().execute(
            "SELECT id,name,description FROM apps WHERE id=?", (app_id,)
        ).fetchone()
        return App(*row) if row else None

    def get_app_by_name(self, name: str) -> Optional[App]:
        row = self._conn().execute(
            "SELECT id,name,description FROM apps WHERE name=?", (name,)
        ).fetchone()
        return App(*row) if row else None

    def list_apps(self) -> List[App]:
        return [App(*r) for r in self._conn().execute(
            "SELECT id,name,description FROM apps ORDER BY id")]

    def delete_app(self, app_id: int) -> bool:
        with self._lock:
            c = self._conn()
            cur = c.execute("DELETE FROM apps WHERE id=?", (app_id,))
            c.execute("DELETE FROM access_keys WHERE appid=?", (app_id,))
            c.execute("DELETE FROM channels WHERE appid=?", (app_id,))
            c.commit()
            return cur.rowcount > 0

    # -- access keys -----------------------------------------------------------

    def create_access_key(
        self, app_id: int, events: Optional[List[str]] = None, key: Optional[str] = None
    ) -> AccessKey:
        key = key or secrets.token_urlsafe(48)
        with self._lock:
            c = self._conn()
            c.execute(
                "INSERT INTO access_keys(key, appid, events) VALUES (?,?,?)",
                (key, app_id, json.dumps(events or [])),
            )
            c.commit()
        return AccessKey(key=key, app_id=app_id, events=events or [])

    def get_access_key(self, key: str) -> Optional[AccessKey]:
        row = self._conn().execute(
            "SELECT key,appid,events FROM access_keys WHERE key=?", (key,)
        ).fetchone()
        return AccessKey(row[0], row[1], json.loads(row[2])) if row else None

    def list_access_keys(self, app_id: Optional[int] = None) -> List[AccessKey]:
        if app_id is None:
            rows = self._conn().execute("SELECT key,appid,events FROM access_keys")
        else:
            rows = self._conn().execute(
                "SELECT key,appid,events FROM access_keys WHERE appid=?", (app_id,))
        return [AccessKey(r[0], r[1], json.loads(r[2])) for r in rows]

    def delete_access_key(self, key: str) -> bool:
        with self._lock:
            c = self._conn()
            cur = c.execute("DELETE FROM access_keys WHERE key=?", (key,))
            c.commit()
            return cur.rowcount > 0

    # -- channels --------------------------------------------------------------

    def create_channel(self, app_id: int, name: str) -> Channel:
        with self._lock:
            c = self._conn()
            cur = c.execute(
                "INSERT INTO channels(name, appid) VALUES (?,?)", (name, app_id))
            c.commit()
            assert cur.lastrowid is not None
            return Channel(id=cur.lastrowid, name=name, app_id=app_id)

    def get_channel_by_name(self, app_id: int, name: str) -> Optional[Channel]:
        row = self._conn().execute(
            "SELECT id,name,appid FROM channels WHERE appid=? AND name=?",
            (app_id, name)).fetchone()
        return Channel(*row) if row else None

    def list_channels(self, app_id: int) -> List[Channel]:
        return [Channel(*r) for r in self._conn().execute(
            "SELECT id,name,appid FROM channels WHERE appid=? ORDER BY id", (app_id,))]

    def delete_channel(self, channel_id: int) -> bool:
        with self._lock:
            c = self._conn()
            cur = c.execute("DELETE FROM channels WHERE id=?", (channel_id,))
            c.commit()
            return cur.rowcount > 0

    # -- engine instances ------------------------------------------------------

    def insert_engine_instance(self, ei: EngineInstance) -> None:
        with self._lock:
            c = self._conn()
            c.execute(
                "INSERT OR REPLACE INTO engine_instances VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)",
                (
                    ei.id, ei.status, format_event_time(ei.start_time),
                    format_event_time(ei.end_time) if ei.end_time else None,
                    ei.engine_factory, ei.engine_variant, ei.batch,
                    json.dumps(ei.env), json.dumps(ei.mesh_conf),
                    ei.data_source_params, ei.preparator_params,
                    ei.algorithms_params, ei.serving_params,
                ),
            )
            c.commit()

    @staticmethod
    def _ei_from_row(r) -> EngineInstance:
        return EngineInstance(
            id=r[0], status=r[1],
            start_time=parse_event_time(r[2]),
            end_time=parse_event_time(r[3]) if r[3] else None,
            engine_factory=r[4], engine_variant=r[5], batch=r[6],
            env=json.loads(r[7]), mesh_conf=json.loads(r[8]),
            data_source_params=r[9], preparator_params=r[10],
            algorithms_params=r[11], serving_params=r[12],
        )

    def get_engine_instance(self, instance_id: str) -> Optional[EngineInstance]:
        row = self._conn().execute(
            "SELECT * FROM engine_instances WHERE id=?", (instance_id,)).fetchone()
        return self._ei_from_row(row) if row else None

    def update_engine_instance(self, ei: EngineInstance) -> None:
        self.insert_engine_instance(ei)

    def get_latest_completed_engine_instance(
        self, engine_factory: str, engine_variant: str = ""
    ) -> Optional[EngineInstance]:
        """Reference semantics: deploy loads the latest COMPLETED instance
        for (engineFactory, variant) ([U] EngineInstances.getLatestCompleted)."""
        q = ("SELECT * FROM engine_instances WHERE status='COMPLETED' "
             "AND engineFactory=?")
        args: List[Any] = [engine_factory]
        if engine_variant:
            q += " AND engineVariant=?"
            args.append(engine_variant)
        q += " ORDER BY startTime DESC LIMIT 1"
        row = self._conn().execute(q, args).fetchone()
        return self._ei_from_row(row) if row else None

    def list_engine_instances(self) -> List[EngineInstance]:
        return [self._ei_from_row(r) for r in self._conn().execute(
            "SELECT * FROM engine_instances ORDER BY startTime DESC")]

    # -- evaluation instances --------------------------------------------------

    def insert_evaluation_instance(self, vi: EvaluationInstance) -> None:
        with self._lock:
            c = self._conn()
            c.execute(
                "INSERT OR REPLACE INTO evaluation_instances VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                (
                    vi.id, vi.status, format_event_time(vi.start_time),
                    format_event_time(vi.end_time) if vi.end_time else None,
                    vi.evaluation_class, vi.engine_params_generator_class,
                    vi.batch, json.dumps(vi.env), vi.evaluator_results,
                    vi.evaluator_results_html, vi.evaluator_results_json,
                ),
            )
            c.commit()

    @staticmethod
    def _vi_from_row(r) -> EvaluationInstance:
        return EvaluationInstance(
            id=r[0], status=r[1],
            start_time=parse_event_time(r[2]),
            end_time=parse_event_time(r[3]) if r[3] else None,
            evaluation_class=r[4], engine_params_generator_class=r[5],
            batch=r[6], env=json.loads(r[7]), evaluator_results=r[8],
            evaluator_results_html=r[9], evaluator_results_json=r[10],
        )

    def get_evaluation_instance(self, instance_id: str) -> Optional[EvaluationInstance]:
        row = self._conn().execute(
            "SELECT * FROM evaluation_instances WHERE id=?", (instance_id,)).fetchone()
        return self._vi_from_row(row) if row else None

    def update_evaluation_instance(self, vi: EvaluationInstance) -> None:
        self.insert_evaluation_instance(vi)

    def list_evaluation_instances(self) -> List[EvaluationInstance]:
        return [self._vi_from_row(r) for r in self._conn().execute(
            "SELECT * FROM evaluation_instances ORDER BY startTime DESC")]

    def new_instance_id(self) -> str:
        return utcnow().strftime("%Y%m%d%H%M%S") + "-" + secrets.token_hex(4)
