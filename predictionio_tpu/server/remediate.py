"""Playbook-driven auto-remediation: ``pio doctor --act`` and the
router-resident loop behind the autoscaler.

``pio doctor`` ranks findings; this module closes the loop by mapping
each finding *kind* onto a **playbook** — the machine-readable form of
the prose runbooks operations.md used to carry:

- ``restart_replica``  — bounce a wedged replica through the
  :class:`~predictionio_tpu.tools.supervise.ReplicaPool` (or the
  router's ``POST /pool/restart`` from an ops box);
- ``rollback_model``   — ``ModelRegistry.rollback`` + rolling fleet
  reload, for a fast burn that follows a model promotion;
- ``clamp_tenant``     — rewrite quotas.json to clamp a hot tenant's
  ingest rate (hot-reloaded fleet-wide within ~1s);
- ``exclude_probe``    — pause the router's synthetic prober for a
  window (and auto-resume), when the canary itself is the burn.

Playbooks are declared in ``conf/remediations.json`` (see
docs/operations.md "Self-healing fleet" for the contract). The engine
is **dry-run by default**: :meth:`RemediationEngine.plan` always
prints what it WOULD do; only ``--yes`` (or the autoscaler's
``auto_remediate``) executes.

Guardrails, each drilled by a fault site:

- every action re-verifies its target against live state immediately
  before acting — ``remediate.wrong_target`` corrupts the selected
  target and the verification must refuse (never restart a healthy
  replica because a finding went stale);
- per-playbook rate limits bound actions per window —
  ``remediate.storm`` floods the engine with repeat findings and the
  limiter, not luck, must hold;
- a fenced one-remediation-in-flight file lock serializes concurrent
  actors (two ``pio doctor --act --yes`` runs, or doctor racing the
  autoscaler's remediator).

Everything here is importable without jax — ``pio doctor`` runs on
ops boxes.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from predictionio_tpu.utils import faults
from predictionio_tpu.utils.metrics import REGISTRY

DEFAULT_PLAYBOOKS_PATH = os.path.join("conf", "remediations.json")

#: built-in contract, mirrored by conf/remediations.json — the file
#: wins when present, so operators tune windows without code changes
DEFAULT_PLAYBOOKS_DOC: Dict[str, Any] = {
    "playbooks": [
        {"name": "restart-wedged-replica",
         "match": {"kinds": ["replica-down", "replica-not-ready",
                             "breaker-open"], "minSeverity": 1},
         "action": "restart_replica",
         "rateLimit": {"max": 2, "windowSec": 600}},
        {"name": "rollback-model-generation",
         "match": {"kinds": ["model-regression"], "minSeverity": 1},
         "action": "rollback_model",
         "rateLimit": {"max": 1, "windowSec": 3600}},
        {"name": "clamp-hot-tenant",
         "match": {"kinds": ["tenant-pressure"], "minSeverity": 1},
         "action": "clamp_tenant",
         "params": {"rateFactor": 0.5, "shedRate": 100},
         "rateLimit": {"max": 2, "windowSec": 1800}},
        {"name": "probe-exclusion",
         "match": {"kinds": ["probe-failing"], "minSeverity": 1},
         "action": "exclude_probe",
         "params": {"resumeAfterSec": 600},
         "rateLimit": {"max": 2, "windowSec": 3600}},
    ],
}

_ACTIONS = ("restart_replica", "rollback_model", "clamp_tenant",
            "exclude_probe")


@dataclass
class Playbook:
    """One finding-kind → action mapping with its own rate limit."""

    name: str
    action: str
    kinds: Tuple[str, ...]
    min_severity: int = 1
    params: Dict[str, Any] = field(default_factory=dict)
    rate_max: int = 2
    rate_window: float = 600.0

    @classmethod
    def from_doc(cls, doc: Dict[str, Any]) -> "Playbook":
        match = doc.get("match") or {}
        rl = doc.get("rateLimit") or {}
        action = doc.get("action")
        if action not in _ACTIONS:
            raise ValueError(
                f"playbook {doc.get('name')!r}: unknown action {action!r} "
                f"(expected one of {_ACTIONS})")
        return cls(
            name=str(doc.get("name") or action),
            action=action,
            kinds=tuple(match.get("kinds") or ()),
            min_severity=int(match.get("minSeverity", 1)),
            params=dict(doc.get("params") or {}),
            rate_max=int(rl.get("max", 2)),
            rate_window=float(rl.get("windowSec", 600)),
        )

    def matches(self, finding: Dict[str, Any]) -> bool:
        return (finding.get("kind") in self.kinds
                and int(finding.get("severity", 0)) >= self.min_severity)


def load_playbooks(path: Optional[str] = None) -> List[Playbook]:
    """``conf/remediations.json`` when readable, else the built-in
    contract. A torn/garbled file is a loud error for an explicit
    ``--remediations PATH``, a silent fallback for the default path —
    remediation config must never take the doctor down."""
    doc = DEFAULT_PLAYBOOKS_DOC
    explicit = path is not None
    path = path or DEFAULT_PLAYBOOKS_PATH
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        if explicit:
            raise
    return [Playbook.from_doc(p) for p in doc.get("playbooks") or []]


def finding_target(finding: Dict[str, Any], action: str) -> Optional[str]:
    """The entity an action operates on, from the finding's structured
    fields (see ``utils/incidents.diagnose``)."""
    if action == "restart_replica":
        url = finding.get("replica") or ""
        # findings carry http:// URLs; the pool and router speak
        # host:port names
        return url.split("://", 1)[-1].rstrip("/") or None
    if action == "clamp_tenant":
        return finding.get("app")
    if action == "rollback_model":
        return "champion"
    if action == "exclude_probe":
        return "probe"
    return None


class RemediationEngine:
    """Plan and (with explicit consent) execute playbook actions.

    ``actuator`` supplies the verbs: an object with ``verify(action,
    target) -> (ok, why)`` plus one method per action name. Two ship
    with the tree: :class:`RouterActuator` (in-process, used by the
    autoscaler's remediator) and :class:`OpsActuator` (HTTP + storage
    home, used by ``pio doctor --act``).
    """

    def __init__(self, actuator: Any,
                 playbooks: Optional[List[Playbook]] = None,
                 *, lock_path: Optional[str] = None,
                 lock_stale: float = 120.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_action: Optional[Callable[[Dict[str, Any]], Any]] = None,
                 log_size: int = 256) -> None:
        self.actuator = actuator
        self.playbooks = (playbooks if playbooks is not None
                          else load_playbooks())
        self.lock_path = lock_path
        self.lock_stale = lock_stale
        self.clock = clock
        self.on_action = on_action
        #: playbook name → monotonic times of executed actions
        self._rate: Dict[str, Deque[float]] = {}
        #: (playbook, target) → last attempt time (transition dedup for
        #: the auto loop: a finding that persists must not re-fire)
        self._attempted: Dict[Tuple[str, str], float] = {}
        self.log: Deque[Dict[str, Any]] = deque(maxlen=log_size)
        self._m_actions = REGISTRY.counter(
            "pio_remediate_actions_total",
            "Remediation playbook outcomes",
            ("playbook", "result"))

    # -- planning --------------------------------------------------------------

    def match(self, finding: Dict[str, Any]) -> Optional[Playbook]:
        for pb in self.playbooks:
            if pb.matches(finding):
                return pb
        return None

    def _rate_limited(self, pb: Playbook, charge: bool = False) -> bool:
        times = self._rate.setdefault(pb.name, deque())
        now = self.clock()
        while times and now - times[0] > pb.rate_window:
            times.popleft()
        if len(times) >= pb.rate_max:
            return True
        if charge:
            times.append(now)
        return False

    def plan(self, findings: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        """Map findings onto playbook entries — pure, no side effects,
        safe to print. One entry per (playbook, target), first finding
        wins."""
        entries: List[Dict[str, Any]] = []
        seen: set = set()
        for f in findings:
            pb = self.match(f)
            if pb is None:
                continue
            target = finding_target(f, pb.action)
            if target is None or (pb.name, target) in seen:
                continue
            seen.add((pb.name, target))
            entries.append({
                "playbook": pb.name,
                "action": pb.action,
                "target": target,
                "params": dict(pb.params),
                "finding": {"kind": f.get("kind"),
                            "severity": f.get("severity"),
                            "title": f.get("title")},
                "rateLimited": self._rate_limited(pb),
            })
        return entries

    # -- execution -------------------------------------------------------------

    def _acquire_lock(self) -> bool:
        if not self.lock_path:
            return True
        for _ in range(2):
            try:
                fd = os.open(self.lock_path,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.write(fd, str(os.getpid()).encode("ascii"))
                os.close(fd)
                return True
            except FileExistsError:
                try:
                    age = time.time() - os.stat(self.lock_path).st_mtime
                    if age > self.lock_stale:
                        os.unlink(self.lock_path)  # orphan: break + retry
                        continue
                except OSError:
                    continue
                return False
        return False

    def _release_lock(self) -> None:
        if self.lock_path:
            try:
                os.unlink(self.lock_path)
            except OSError:
                pass

    def _finish(self, entry: Dict[str, Any], result: str) -> Dict[str, Any]:
        out = dict(entry, result=result, at=time.time())
        family = result.split(":", 1)[0].split(" ", 1)[0]
        self._m_actions.inc((entry["playbook"], family))
        self.log.append(out)
        if self.on_action is not None:
            try:
                self.on_action(out)
            except Exception:  # noqa: BLE001 — timeline is best-effort
                pass
        return out

    def execute(self, entries: List[Dict[str, Any]],
                yes: bool = False) -> List[Dict[str, Any]]:
        """Run a plan. ``yes=False`` is the dry run: every entry comes
        back ``result="dry-run"`` and NOTHING is touched. With
        ``yes=True``, each entry passes (in order) the one-in-flight
        lock, the per-playbook rate limit, and target verification —
        then the actuator verb runs."""
        if not yes:
            return [dict(e, result="dry-run") for e in entries]
        if not self._acquire_lock():
            return [self._finish(e, "locked") for e in entries]
        by_name = {pb.name: pb for pb in self.playbooks}
        results = []
        try:
            for entry in entries:
                pb = by_name.get(entry["playbook"])
                if pb is None:
                    results.append(self._finish(entry, "error: unknown "
                                                       "playbook"))
                    continue
                if self._rate_limited(pb):
                    results.append(self._finish(entry, "rate-limited"))
                    continue
                target = entry["target"]
                try:
                    faults.inject("remediate.wrong_target")
                except faults.FaultError:
                    # the drill: target selection went wrong —
                    # verification below must catch it
                    wrong = getattr(self.actuator, "wrong_target", None)
                    target = (wrong(entry["action"], target) if wrong
                              else f"{target}:wrong")
                ok, why = self.actuator.verify(entry["action"], target)
                if not ok:
                    results.append(self._finish(
                        dict(entry, target=target), f"refused: {why}"))
                    continue
                try:
                    verb = getattr(self.actuator, entry["action"])
                    detail = verb(target, **entry.get("params") or {})
                except Exception as e:  # noqa: BLE001 — per-entry isolation
                    results.append(self._finish(
                        entry, f"error: {type(e).__name__}: {e}"))
                    continue
                self._rate_limited(pb, charge=True)
                done = dict(entry)
                if detail:
                    done["detail"] = detail
                results.append(self._finish(done, "executed"))
        finally:
            self._release_lock()
        return results

    # -- the autoscaler's loop -------------------------------------------------

    def auto_remediate(self,
                       findings: List[Dict[str, Any]]
                       ) -> List[Dict[str, Any]]:
        """Unattended remediation for the router-resident loop: plan,
        dedup persistent findings (a replica that STAYS broken fires
        once per rate window, not once per tick), execute. The
        ``remediate.storm`` drill bypasses the dedup so the rate
        limiter alone must bound the blast radius."""
        storm = False
        try:
            faults.inject("remediate.storm")
        except faults.FaultError:
            storm = True
        by_name = {pb.name: pb for pb in self.playbooks}
        now = self.clock()
        entries = []
        for entry in self.plan(findings):
            pb = by_name[entry["playbook"]]
            key = (entry["playbook"], entry["target"])
            last = self._attempted.get(key)
            if not storm and last is not None and now - last < pb.rate_window:
                continue
            self._attempted[key] = now
            entries.append(entry)
        if not entries:
            return []
        return self.execute(entries, yes=True)


class RouterActuator:
    """In-process verbs for the router-resident remediator: restart
    through the attached :class:`ReplicaPool`, verify against live
    ``Replica`` state, pause the prober, clamp via the router's own
    quota store. ``rollback_model`` is NOT available here — the router
    has no storage home; rollbacks run via ``pio doctor --act`` on a
    box that does."""

    def __init__(self, router: Any, pool: Any = None) -> None:
        self.router = router
        self.pool = pool

    def _replica(self, target: str) -> Any:
        for rep in self.router.replicas:
            if rep.name == target:
                return rep
        return None

    def verify(self, action: str, target: str) -> Tuple[bool, str]:
        if action == "restart_replica":
            rep = self._replica(target)
            if rep is None:
                return False, f"unknown replica {target!r}"
            if (rep.state in ("down", "not-ready")
                    or rep.breaker.state == "open"
                    or rep.health_failures > 0):
                return True, ""
            return False, (f"replica {target} is {rep.state} with breaker "
                           f"{rep.breaker.state} — not wedged")
        if action == "restart_replica" or target is None:
            return False, "no target"
        return True, ""

    def wrong_target(self, action: str, target: str) -> str:
        """The ``remediate.wrong_target`` drill's corruption: the most
        plausible WRONG answer — a healthy replica — so verification
        is what must save us, not an unresolvable name."""
        if action == "restart_replica":
            for rep in self.router.replicas:
                if (rep.name != target and rep.state == "ok"
                        and rep.breaker.state == "closed"):
                    return rep.name
        return f"{target}:wrong"

    def restart_replica(self, target: str) -> str:
        if self.pool is None:
            raise RuntimeError("no replica pool attached to this router")
        self.pool.restart_replica(target)
        return f"pool restart requested for {target}"

    def exclude_probe(self, target: str, resumeAfterSec: float = 600,
                      **_: Any) -> str:
        self.router.pause_probe(float(resumeAfterSec))
        return f"prober paused for {resumeAfterSec:g}s"

    def clamp_tenant(self, app: str, rateFactor: float = 0.5,
                     shedRate: float = 100, **_: Any) -> str:
        return _clamp_tenant(self.router.quotas, app, rateFactor, shedRate)

    def rollback_model(self, target: str, **_: Any) -> str:
        raise RuntimeError(
            "rollback_model needs a storage home — run "
            "`pio doctor --act` where PIO_HOME points at the models")


class OpsActuator:
    """jax-free verbs for ``pio doctor --act`` on an ops box: replica
    and probe actions go over HTTP to the router; model rollback and
    tenant clamps act on the storage home directly."""

    def __init__(self, url: Optional[str] = None,
                 home: Optional[str] = None,
                 timeout: float = 30.0) -> None:
        self.url = url.rstrip("/") if url else None
        self.home = home
        self.timeout = timeout

    def _http(self, method: str, path: str) -> Dict[str, Any]:
        import urllib.request

        if not self.url:
            raise RuntimeError("this action needs --url (a live router)")
        req = urllib.request.Request(self.url + path, data=b"",
                                     method=method)
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            body = r.read()
        try:
            return json.loads(body) if body else {}
        except ValueError:
            return {}

    def verify(self, action: str, target: str) -> Tuple[bool, str]:
        if action == "restart_replica":
            try:
                doc = self._http("GET", "/router/status")
            except Exception as e:  # noqa: BLE001 — verification must not 500
                return False, f"router status unreachable: {e}"
            for rep in doc.get("replicas") or []:
                name = (rep.get("url") or "").split("://", 1)[-1]
                if name != target:
                    continue
                if (rep.get("state") in ("down", "not-ready")
                        or rep.get("breaker") == "open"):
                    return True, ""
                return False, (f"replica {target} is {rep.get('state')} "
                               f"with breaker {rep.get('breaker')} — "
                               "not wedged")
            return False, f"unknown replica {target!r}"
        if not target:
            return False, "no target"
        return True, ""

    def restart_replica(self, target: str) -> str:
        out = self._http("POST", f"/pool/restart?replica={target}")
        if not out.get("ok"):
            raise RuntimeError(f"router refused restart: {out}")
        return f"router restarted {target}"

    def exclude_probe(self, target: str, resumeAfterSec: float = 600,
                      **_: Any) -> str:
        self._http("POST", f"/probe?pause={float(resumeAfterSec):g}")
        return f"prober paused for {resumeAfterSec:g}s"

    def clamp_tenant(self, app: str, rateFactor: float = 0.5,
                     shedRate: float = 100, **_: Any) -> str:
        from predictionio_tpu.server.tenancy import TenantQuotas

        if not self.home:
            raise RuntimeError("clamp_tenant needs a storage home "
                               "(PIO_HOME) for quotas.json")
        return _clamp_tenant(TenantQuotas.for_home(self.home), app,
                             rateFactor, shedRate)

    def rollback_model(self, target: str, **_: Any) -> str:
        from predictionio_tpu.storage.models import model_registry
        from predictionio_tpu.storage.registry import (Storage,
                                                       StorageConfig)

        cfg = (StorageConfig(home=self.home) if self.home
               else StorageConfig.from_env())
        storage = Storage(cfg)
        registry = model_registry(storage)
        entry = registry.rollback()
        registry.sync_meta(storage.meta)
        detail = f"rolled back to generation {entry.get('gen')}"
        if self.url:
            out = self._http("POST", "/router/reload?rolling=1")
            detail += (" + rolling reload ok" if out.get("ok")
                       else f" + rolling reload FAILED: {out}")
        return detail


def _clamp_tenant(quotas: Any, app: str, rate_factor: float,
                  shed_rate: float) -> str:
    """Shared clamp: halve (``rateFactor``) a limited tenant, or pin an
    unlimited one to ``shedRate`` — quotas.json is hot-reloaded by
    every ingest gate within ~1s, so the clamp lands fleet-wide without
    restarts."""
    current = float(quotas.describe(app).get("rate") or 0.0)
    new_rate = (max(1.0, current * rate_factor) if current > 0
                else float(shed_rate))
    quotas.set_quota(app, rate=new_rate, burst=new_rate)
    return (f"app {app} ingest clamped "
            f"{current:g} -> {new_rate:g} events/s")
