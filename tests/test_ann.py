"""ANN retrieval subsystem (predictionio_tpu/ann): PQ codec round-trip,
ADC serving parity vs the exact path, AOT zero-compile contract, index
blob integrity (the ``ann.index.corrupt`` drill: ``pio fsck`` detects,
``/reload`` refuses, champion keeps serving), and the unknown-user
contract on the ANN path."""

import json
import os

import numpy as np
import pytest

from predictionio_tpu import ann
from predictionio_tpu.ann import pq
from predictionio_tpu.ann.index import PQIndex
from predictionio_tpu.utils import faults
from predictionio_tpu.utils.faults import FAULTS
from predictionio_tpu.utils.integrity import IntegrityError

TT_FACTORY = "predictionio_tpu.templates.twotower.engine:engine_factory"


@pytest.fixture(autouse=True)
def disarm_faults():
    FAULTS.disarm()
    yield
    FAULTS.disarm()


@pytest.fixture(autouse=True, scope="module")
def _restore_aot_counters():
    """pio_aot_cache_lookups_total / pio_predict_dispatch_total are
    process-global; later test files assert absolute values on them, so
    this module's warmup compiles must not leak out."""
    from predictionio_tpu.server import aot as aot_mod

    counters = (aot_mod.EXECUTABLES._m_lookups, aot_mod._DISPATCHES)
    snaps = [dict(c._values) for c in counters]
    yield
    for c, snap in zip(counters, snaps):
        with c._lock:
            c._values.clear()
            c._values.update(snap)


def _clustered(n, d, centers, seed=0, noise=0.2):
    """Unit-norm corpus with cluster structure — recall@k against the
    exact scan is only meaningful when neighborhoods exist."""
    rng = np.random.default_rng(seed)
    C = rng.standard_normal((centers, d)).astype(np.float32)
    V = (C[rng.integers(0, centers, size=n)]
         + noise * rng.standard_normal((n, d)).astype(np.float32))
    V /= np.linalg.norm(V, axis=1, keepdims=True) + 1e-9
    return V


# -- PQ codec ------------------------------------------------------------------


class TestPQCodec:
    def test_encode_decode_roundtrip_bounds(self):
        V = _clustered(1500, 16, 24, seed=1)
        cb = pq.train_codebooks(V, 4, 32, iters=6, sample=1500)
        assert cb.shape == (4, 32, 4) and cb.dtype == np.float32
        codes = pq.encode(V, cb)
        assert codes.shape == (1500, 4) and codes.dtype == np.uint8
        rec = pq.decode(codes, cb)
        assert rec.shape == V.shape
        mse = pq.reconstruction_mse(V, cb, codes)
        # quantizing must beat the zero-codebook baseline (= mean ‖v‖²/d)
        assert mse < float(np.mean(V * V))
        # and the chunked encode is the true argmin assignment: no
        # other centroid combination reconstructs any row better
        err = V - rec
        assert float(np.mean(np.sum(err * err, axis=1))) < 1.0  # unit rows

    def test_geometry_validation(self):
        V = np.zeros((10, 15), np.float32)
        with pytest.raises(ValueError, match="split evenly"):
            pq.train_codebooks(V, 4, 16, sample=10)
        with pytest.raises(ValueError, match="out of range"):
            pq.train_codebooks(np.zeros((10, 16), np.float32), 4, 300,
                               sample=10)

    def test_tiny_corpus_fewer_rows_than_centroids(self):
        V = _clustered(12, 8, 3, seed=2)
        idx = ann.build_index(V, 2, 16, iters=2, sample=12)
        assert idx.codes.shape == (12, 2)
        assert np.isfinite(idx.codebooks).all()


# -- wire format + integrity ---------------------------------------------------


class TestIndexBlob:
    def test_blob_roundtrip_and_manifest(self, tmp_path):
        V = _clustered(600, 16, 12, seed=3)
        idx = ann.build_index(V, 4, 16, iters=3, sample=600)
        back = PQIndex.from_bytes(idx.to_bytes())
        np.testing.assert_array_equal(back.codes, idx.codes)
        np.testing.assert_array_equal(back.codebooks, idx.codebooks)
        assert back.meta["build_sec"] == idx.meta["build_sec"]

        d = str(tmp_path)
        ann.save_index(idx, d)
        loaded = ann.load_index(d)
        np.testing.assert_array_equal(loaded.codes, idx.codes)
        with open(os.path.join(d, ann.MANIFEST_BASENAME)) as f:
            man = json.load(f)
        assert man["m"] == 4 and man["k"] == 16 and man["n_items"] == 600
        assert man["code_bytes"] == idx.code_bytes()
        assert man["hbm_estimate_bytes"] == idx.hbm_estimate_bytes()
        assert len(man["sha256"]) == 64
        assert ann.load_index(str(tmp_path / "nope")) is None

    def test_corrupt_blob_is_refused_then_loads_when_disarmed(
            self, tmp_path):
        V = _clustered(300, 8, 6, seed=4)
        ann.save_index(ann.build_index(V, 2, 8, iters=2, sample=300),
                       str(tmp_path))
        FAULTS.arm("ann.index.corrupt")
        with pytest.raises(IntegrityError):
            ann.load_index(str(tmp_path))
        FAULTS.disarm()
        assert ann.load_index(str(tmp_path)) is not None

    def test_structural_damage_raises_integrity_error(self):
        with pytest.raises(IntegrityError, match="corrupt"):
            PQIndex.from_bytes(b"NOTANANN" + b"\x00" * 64)
        V = _clustered(100, 8, 4, seed=5)
        blob = bytearray(ann.build_index(V, 2, 8, iters=2,
                                         sample=100).to_bytes())
        blob[len(blob) // 2] ^= 0xFF   # payload damage → digest mismatch
        with pytest.raises(IntegrityError):
            PQIndex.from_bytes(bytes(blob))

    def test_fsck_detects_corrupt_index_file(self, tmp_path, monkeypatch,
                                             capsys):
        from predictionio_tpu.data.pel_integrity import fsck_home
        from predictionio_tpu.tools.cli import main as cli_main

        monkeypatch.delenv("PIO_SCAN_CACHE_DIR", raising=False)
        home = tmp_path / "home"
        algo_dir = home / "models" / "inst1" / "twotower"
        algo_dir.mkdir(parents=True)
        V = _clustered(200, 8, 4, seed=6)
        ann.save_index(ann.build_index(V, 2, 8, iters=2, sample=200),
                       str(algo_dir))

        rep = fsck_home(str(home))
        assert rep["corrupt"] == 0

        blob_path = algo_dir / ann.INDEX_BASENAME
        raw = bytearray(blob_path.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        blob_path.write_bytes(bytes(raw))
        rep = fsck_home(str(home))
        assert rep["corrupt"] == 1
        bad = [r for r in rep["artifacts"] if r["status"] == "corrupt"]
        assert bad and bad[0]["artifact"] == "ann_index"

        try:
            cli_main(["fsck", "--home", str(home), "--json"])
            code = 0
        except SystemExit as e:
            code = int(e.code or 0)
        assert code == 2
        doc = json.loads(capsys.readouterr().out)
        assert doc["corrupt"] == 1

    def test_fsck_detects_corruption_via_fault_site(self, tmp_path,
                                                    monkeypatch):
        from predictionio_tpu.data.pel_integrity import fsck_home

        monkeypatch.delenv("PIO_SCAN_CACHE_DIR", raising=False)
        home = tmp_path / "home"
        algo_dir = home / "models" / "inst1" / "twotower"
        algo_dir.mkdir(parents=True)
        V = _clustered(200, 8, 4, seed=7)
        ann.save_index(ann.build_index(V, 2, 8, iters=2, sample=200),
                       str(algo_dir))
        assert fsck_home(str(home))["corrupt"] == 0
        faults.FAULTS.arm("ann.index.corrupt")
        assert fsck_home(str(home))["corrupt"] == 1


# -- ADC serving parity --------------------------------------------------------


class TestANNServing:
    def _fixture(self, n=3000, d=16, shortlist=256, seed=8, centers=40):
        V = _clustered(n, d, centers, seed=seed)
        rng = np.random.default_rng(seed + 1)
        U = (V[rng.integers(0, n, size=64)]
             + 0.1 * rng.standard_normal((64, d)).astype(np.float32))
        U /= np.linalg.norm(U, axis=1, keepdims=True) + 1e-9
        idx = ann.build_index(V, 4, 64, iters=5, sample=n)
        return U, V, ann.ANNScorer(U, V, idx, shortlist=shortlist)

    def test_recall_at_10_vs_exact(self):
        U, V, scorer = self._fixture()
        exact_top = np.argsort(-(U @ V.T), axis=1)[:, :10]
        got = scorer.recommend_batch(np.arange(len(U), dtype=np.int32), 10)
        hits = sum(np.intersect1d(iv, et).size
                   for (iv, _), et in zip(got, exact_top))
        assert hits / (len(U) * 10) >= 0.95

    def test_pad_row_masking_parity_across_buckets(self):
        from predictionio_tpu.server.aot import BucketLadder

        U, V, scorer = self._fixture(n=2500)
        ladder = BucketLadder([2, 4, 8])
        scorer.warm_buckets(ladder, ks=(10,))
        singles = {u: scorer.recommend(u, 10) for u in range(8)}
        for B in (1, 2, 3, 5, 7, 8):   # every bucket, padded and full
            got = scorer.recommend_batch(np.arange(B, dtype=np.int32), 10)
            for u, (iv, vv) in enumerate(got):
                np.testing.assert_array_equal(iv, singles[u][0])
                np.testing.assert_allclose(vv, singles[u][1], rtol=1e-5)

    def test_zero_compiles_after_warmup_sweep(self):
        from predictionio_tpu.server import aot as aot_mod
        from predictionio_tpu.server.aot import BucketLadder

        def jit_gaps():
            return sum(v for k, v in aot_mod._DISPATCHES._values.items()
                       if k[1] == "jit")

        U, V, scorer = self._fixture(n=2200)
        ladder = BucketLadder([2, 4, 8])
        warm = scorer.warm_buckets(ladder, ks=(10,))
        assert warm["targets"] == 3
        compiles0 = aot_mod.EXECUTABLES.counts().get("compile", 0)
        gaps0 = jit_gaps()
        for B in (1, 2, 3, 4, 6, 8):
            scorer.recommend_batch(np.arange(B, dtype=np.int32), 10)
        assert aot_mod.EXECUTABLES.counts().get("compile", 0) == compiles0
        assert jit_gaps() == gaps0

    def test_exclusion_filtering(self):
        U, V, scorer = self._fixture(n=2100)
        [(iv, _)] = scorer.recommend_batch(np.asarray([0]), 5)
        [(iv2, _)] = scorer.recommend_batch(
            np.asarray([0]), 5, exclude=[iv[:2]])
        assert not np.intersect1d(iv2, iv[:2]).size

    @pytest.mark.slow
    def test_big_corpus_recall_and_streamed_shortlist(self):
        """200k items exercises the streamed (scan-tiled) ADC shortlist
        path (> 2 tiles at the 32768-column chunk)."""
        U, V, scorer = self._fixture(n=200_000, d=16, shortlist=512,
                                     seed=9, centers=1600)
        exact_top = np.argsort(-(U @ V.T), axis=1)[:, :10]
        got = scorer.recommend_batch(np.arange(len(U), dtype=np.int32), 10)
        hits = sum(np.intersect1d(iv, et).size
                   for (iv, _), et in zip(got, exact_top))
        assert hits / (len(U) * 10) >= 0.9


# -- template integration: train → deploy → query → reload ---------------------


def _tt_variant(ann_on: bool):
    algo = {"embedDim": 16, "outDim": 16, "hidden": [32], "epochs": 3,
            "batchSize": 128}
    if ann_on:
        algo.update({"ann": True, "annM": 4, "annK": 16, "annIters": 2,
                     "annShortlist": 16, "annSample": 512})
    return {
        "engineFactory": TT_FACTORY,
        "datasource": {"params": {"appName": "ANNApp"}},
        "algorithms": [{"name": "twotower", "params": algo}],
    }


def _seed_tt_events(storage, n_users=20, n_items=16):
    from predictionio_tpu.data.event import Event

    app = storage.meta.create_app("ANNApp")
    storage.events.init_channel(app.id)
    rng = np.random.default_rng(11)
    evs = [Event(event="view", entity_type="user",
                 entity_id=f"u{int(u)}", target_entity_type="item",
                 target_entity_id=f"i{int(i)}")
           for u, i in zip(rng.integers(0, n_users, 400),
                           rng.integers(0, n_items, 400))]
    storage.events.insert_batch(evs, app.id)
    return app


class TestTemplateANN:
    def test_train_deploy_query_and_unknown_user(self, storage,
                                                 monkeypatch):
        from predictionio_tpu.ann.scorer import ANNScorer
        from predictionio_tpu.core.workflow import prepare_deploy, run_train

        monkeypatch.setenv("PIO_ALS_SERVE", "device")
        _seed_tt_events(storage)
        run_train(TT_FACTORY, variant=_tt_variant(True), storage=storage,
                  use_mesh=False)
        deployed = prepare_deploy(engine_factory=TT_FACTORY,
                                  storage=storage)
        model = deployed.models[0]
        assert model.ann_index is not None
        assert isinstance(model._device_scorer(), ANNScorer)
        res = deployed.query({"user": "u1", "num": 5})
        assert len(res["itemScores"]) == 5
        # unknown user → HTTP-level empty result, never a 500 (same
        # contract as the exact path)
        assert deployed.query({"user": "nobody", "num": 3}) == \
            {"itemScores": []}

    def test_ann_results_match_exact_rerank_scores(self, storage,
                                                   monkeypatch):
        """With k′ = catalog size the shortlist covers everything, so
        the ANN path's re-ranked answer must equal the exact path's."""
        from predictionio_tpu.core.workflow import prepare_deploy, run_train

        _seed_tt_events(storage)
        run_train(TT_FACTORY, variant=_tt_variant(True), storage=storage,
                  use_mesh=False)
        monkeypatch.setenv("PIO_ALS_SERVE", "host")
        host = prepare_deploy(engine_factory=TT_FACTORY, storage=storage)
        host_res = host.query({"user": "u2", "num": 5})
        monkeypatch.setenv("PIO_ALS_SERVE", "device")
        dev = prepare_deploy(engine_factory=TT_FACTORY, storage=storage)
        dev_res = dev.query({"user": "u2", "num": 5})
        assert [s["item"] for s in dev_res["itemScores"]] == \
            [s["item"] for s in host_res["itemScores"]]
        np.testing.assert_allclose(
            [s["score"] for s in dev_res["itemScores"]],
            [s["score"] for s in host_res["itemScores"]], rtol=1e-4)

    def test_reload_refuses_corrupt_index_champion_keeps_serving(
            self, storage, monkeypatch):
        from predictionio_tpu.core.workflow import run_train
        from predictionio_tpu.server.engine_server import EngineServer
        from tests.test_servers import ServerThread, free_port, http

        monkeypatch.setenv("PIO_ALS_SERVE", "device")
        _seed_tt_events(storage)
        first = run_train(TT_FACTORY, variant=_tt_variant(True),
                          storage=storage, use_mesh=False)
        port = free_port()
        server = EngineServer(engine_factory=TT_FACTORY, storage=storage,
                              host="127.0.0.1", port=port)
        with ServerThread(server):
            base = f"http://127.0.0.1:{port}"
            assert http("POST", f"{base}/queries.json",
                        {"user": "u1", "num": 3})[0] == 200
            run_train(TT_FACTORY, variant=_tt_variant(True),
                      storage=storage, use_mesh=False)
            # candidate's index blob is corrupt: /reload must refuse it
            # (prepare_deploy raises IntegrityError) and keep serving
            # the champion
            FAULTS.arm("ann.index.corrupt")
            code, body = http("GET", f"{base}/reload")
            assert code == 500
            assert body["swap"] == "refused"
            assert http("GET", f"{base}/")[1]["engineInstanceId"] == first
            assert http("POST", f"{base}/queries.json",
                        {"user": "u1", "num": 3})[0] == 200
            # drill over: the same candidate now promotes
            FAULTS.disarm()
            code, body = http("GET", f"{base}/reload")
            assert code == 200 and body["engineInstanceId"] != first
