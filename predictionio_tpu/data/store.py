"""App-facing event access: the stable API templates program against.

Equivalent of the reference's ``PEventStore`` / ``LEventStore`` +
``Common`` app-name resolution (reference: [U] data/.../store/ —
unverified, SURVEY.md §2a). Templates call these with an **app name**
(not id); channel by name. Two access shapes:

- :func:`find` / :func:`aggregate_properties` — bulk reads for training
  (the reference's ``PEventStore``; instead of producing an RDD they
  produce Python iterators/dicts that the data pipeline turns into
  columnar numpy/jax arrays).
- :func:`find_by_entity` — low-latency point lookups at serving time
  (the reference's ``LEventStore.findByEntity``, used by the e-commerce
  template for live business rules).
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from predictionio_tpu.data.event import Event, PropertyMap
from predictionio_tpu.storage.registry import Storage, get_storage


def resolve_app_channel(
    app_name: str, channel_name: Optional[str] = None, storage: Optional[Storage] = None
) -> Tuple[int, Optional[int]]:
    st = storage or get_storage()
    app = st.meta.get_app_by_name(app_name)
    if app is None:
        raise ValueError(f"App {app_name!r} does not exist; create it with `pio app new`")
    channel_id: Optional[int] = None
    if channel_name:
        ch = st.meta.get_channel_by_name(app.id, channel_name)
        if ch is None:
            raise ValueError(f"Channel {channel_name!r} does not exist in app {app_name!r}")
        channel_id = ch.id
    return app.id, channel_id


def find(
    app_name: str,
    channel_name: Optional[str] = None,
    start_time: Optional[_dt.datetime] = None,
    until_time: Optional[_dt.datetime] = None,
    entity_type: Optional[str] = None,
    entity_id: Optional[str] = None,
    event_names: Optional[Sequence[str]] = None,
    target_entity_type: Optional[str] = None,
    target_entity_id: Optional[str] = None,
    limit: Optional[int] = None,
    reversed: bool = False,
    storage: Optional[Storage] = None,
) -> Iterator[Event]:
    st = storage or get_storage()
    app_id, channel_id = resolve_app_channel(app_name, channel_name, st)
    return st.events.find(
        app_id,
        channel_id,
        start_time=start_time,
        until_time=until_time,
        entity_type=entity_type,
        entity_id=entity_id,
        event_names=event_names,
        target_entity_type=target_entity_type,
        target_entity_id=target_entity_id,
        limit=limit,
        reversed=reversed,
    )


def aggregate_properties(
    app_name: str,
    entity_type: str,
    channel_name: Optional[str] = None,
    start_time: Optional[_dt.datetime] = None,
    until_time: Optional[_dt.datetime] = None,
    storage: Optional[Storage] = None,
) -> Dict[str, PropertyMap]:
    st = storage or get_storage()
    app_id, channel_id = resolve_app_channel(app_name, channel_name, st)
    return st.events.aggregate_properties(
        app_id, entity_type, channel_id, start_time=start_time, until_time=until_time
    )


def find_by_entity(
    app_name: str,
    entity_type: str,
    entity_id: str,
    channel_name: Optional[str] = None,
    event_names: Optional[Sequence[str]] = None,
    target_entity_type: Optional[str] = None,
    target_entity_id: Optional[str] = None,
    start_time: Optional[_dt.datetime] = None,
    until_time: Optional[_dt.datetime] = None,
    limit: Optional[int] = None,
    latest: bool = True,
    storage: Optional[Storage] = None,
) -> List[Event]:
    """Serving-time point lookup (reference: LEventStore.findByEntity;
    `latest` mirrors its newest-first default)."""
    st = storage or get_storage()
    app_id, channel_id = resolve_app_channel(app_name, channel_name, st)
    return list(
        st.events.find(
            app_id,
            channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            limit=limit,
            reversed=latest,
        )
    )
