"""Chip-free device-dispatch counting for compiled programs.

The r5 VERDICT measured the warm ML-20M ALS train latency-bound at
~8.8k device ops per iteration (1.0% MFU, HBM at 49 of 819 GB/s) — the
cost was DISPATCH COUNT, not FLOPs. This module makes that number a
first-class, hardware-free metric: trace a program to its jaxpr
(``jax.make_jaxpr`` over ``ShapeDtypeStruct``s — no device buffers, no
backend execution) and count the primitive applications the device
would run, expanding control flow the way XLA does:

- ``scan``/``while`` body ops multiply by the trip count (a scan of
  100 slabs IS 100× its body's dispatches on device);
- ``pjit``/``closed_call``/``custom_*_call``/``remat`` recurse into
  their sub-jaxprs (inlined at compile time);
- ``cond`` takes the max over branches (one branch runs);
- a ``pallas_call`` is ONE op — that asymmetry is the whole point of
  the fused gather→Gram work.

The count is an upper-bound proxy (XLA fusion merges some elementwise
neighbors), but it is stable, cheap, and moves in lockstep with the
dispatch wall: `bench.py` emits it next to ``mfu_device`` and
`profile_als.py --opcount` guards the ≥10× collapse without hardware.
"""

from __future__ import annotations

from typing import Optional

# primitives that recurse into exactly one inner jaxpr
_CALL_PRIMS = ("pjit", "closed_call", "core_call", "xla_call", "remat",
               "remat2", "checkpoint", "custom_jvp_call",
               "custom_vjp_call", "custom_vjp_call_jaxpr",
               "shard_map", "jit")


def _inner_jaxprs(eqn):
    """Every ClosedJaxpr/Jaxpr hiding in an eqn's params."""
    import jax.core as jcore

    out = []
    for v in eqn.params.values():
        for j in (v if isinstance(v, (list, tuple)) else [v]):
            if isinstance(j, jcore.ClosedJaxpr):
                out.append(j.jaxpr)
            elif isinstance(j, jcore.Jaxpr):
                out.append(j)
    return out


def count_jaxpr_ops(jaxpr) -> int:
    """Device-op estimate for a (Closed)Jaxpr — see module docstring."""
    import jax.core as jcore

    if isinstance(jaxpr, jcore.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    total = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        inner = _inner_jaxprs(eqn)
        if name == "scan":
            body = count_jaxpr_ops(eqn.params["jaxpr"])
            total += body * int(eqn.params.get("length", 1))
        elif name == "while":
            # ≥1 trip: body + cond once (trip count is data-dependent;
            # ALS programs use scan for anything with known length)
            total += sum(count_jaxpr_ops(j) for j in inner)
        elif name == "cond":
            branches = eqn.params.get("branches", ())
            total += max((count_jaxpr_ops(b) for b in branches),
                         default=0)
        elif name in _CALL_PRIMS and inner:
            total += sum(count_jaxpr_ops(j) for j in inner)
        else:
            # pallas_call lands here: ONE device dispatch, params'
            # kernel jaxpr intentionally NOT recursed
            total += 1
    return total


def count_fn_ops(fn, *avals) -> int:
    """Trace ``fn`` over ShapeDtypeStructs and count device ops."""
    import jax

    return count_jaxpr_ops(jax.make_jaxpr(fn)(*avals))


def _struct_tree(tree):
    """numpy/array pytree → matching ShapeDtypeStruct pytree."""
    import jax
    import numpy as np

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype)
        if not isinstance(a, jax.ShapeDtypeStruct) else a, tree)


def _host_side_bufs(side):
    """Mirror of ``ALSPrepared.device_buffers``'s per-side structure,
    built from the HOST numpy arrays (nothing touches a device)."""
    dense = (() if side.dense is None else
             (side.dense.w_cnt, side.dense.w_val, side.dense.counts))
    return (dense, tuple(
        tuple((b.other_idx, b.vals, b.mask, b.counts)
              + ((b.seg, b.seg_off) if b.seg is not None else ()))
        for b in side.buckets))


def als_iteration_ops(prep, params, gram_mode: str = "off",
                      platform: Optional[str] = "tpu") -> int:
    """Device ops for ONE ALS iteration (two half-steps) at ``prep``'s
    geometry under ``gram_mode`` — traced abstractly for ``platform``
    (default "tpu": count what the CHIP would dispatch, even from a
    chip-free host).

    The Pallas solve preflight is bypassed by tracing with
    ``PIO_PALLAS_SOLVE=1`` when the fused mode would prefer the kernel
    (the preflight EXECUTES on the default backend — meaningless and
    Mosaic-unsupported during an abstract CPU trace of a TPU program).
    """
    import os

    import jax
    import jax.numpy as jnp

    from predictionio_tpu.models import als as als_mod

    p = params
    half = als_mod._make_half(
        p.rank, bool(p.implicit), bool(p.weighted_reg),
        platform=platform, bf16_gather=bool(p.bf16_gather),
        precision=als_mod._gram_precision(),
        gram_mode=("pallas" if gram_mode == "interpret" and
                   platform == "tpu" else gram_mode))
    geom_u, geom_i = prep.u_side.geometry, prep.i_side.geometry

    def step(u_bufs, i_bufs, U, V, reg, alpha):
        U = half(V, u_bufs, geom_u, reg, alpha)
        V = half(U, i_bufs, geom_i, reg, alpha)
        return U, V

    u_bufs = _struct_tree(_host_side_bufs(prep.u_side))
    i_bufs = _struct_tree(_host_side_bufs(prep.i_side))
    U = jax.ShapeDtypeStruct((prep.n_users, p.rank), jnp.float32)
    V = jax.ShapeDtypeStruct((prep.n_items, p.rank), jnp.float32)
    s = jax.ShapeDtypeStruct((), jnp.float32)

    force_solve = (gram_mode in ("pallas", "interpret")
                   and platform == "tpu"
                   and not os.environ.get("PIO_PALLAS_SOLVE"))
    if force_solve:
        os.environ["PIO_PALLAS_SOLVE"] = "1"
    try:
        return count_fn_ops(step, u_bufs, i_bufs, U, V, s, s)
    finally:
        if force_solve:
            del os.environ["PIO_PALLAS_SOLVE"]


def als_dispatch_report(prep, params, platform: Optional[str] = "tpu"
                        ) -> dict:
    """Baseline-vs-fused dispatch counts for one ALS iteration:
    ``{"xla": n, "fused": n, "ratio": xla/fused}`` — the chip-free
    evidence for the dispatch-collapse claim (ISSUE 17 acceptance)."""
    xla = als_iteration_ops(prep, params, "off", platform)
    fused = als_iteration_ops(prep, params, "pallas", platform)
    return {"device_ops_per_iter_xla": xla,
            "device_ops_per_iter": fused,
            "dispatch_collapse_ratio": xla / max(1, fused)}
