"""Prometheus metrics module + /metrics endpoints (SURVEY.md §5)."""

from __future__ import annotations

from predictionio_tpu.utils.metrics import Counter, Histogram, Registry


class TestPrimitives:
    def test_counter_labels(self):
        c = Counter("t_total", "help text", ("app", "status"))
        c.inc(("1", "201"))
        c.inc(("1", "201"), 2)
        c.inc(("2", "400"))
        lines = c.render()
        assert "# TYPE t_total counter" in lines
        assert 't_total{app="1",status="201"} 3' in lines
        assert 't_total{app="2",status="400"} 1' in lines

    def test_histogram_buckets(self):
        h = Histogram("lat_seconds", "h", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.05, 0.5, 5.0):
            h.observe(v)
        lines = h.render()
        assert 'lat_seconds_bucket{le="0.01"} 1' in lines
        assert 'lat_seconds_bucket{le="0.1"} 3' in lines
        assert 'lat_seconds_bucket{le="1"} 4' in lines
        assert 'lat_seconds_bucket{le="+Inf"} 5' in lines
        assert "lat_seconds_count 5" in lines

    def test_registry_get_or_create(self):
        """Re-instantiating a server must reuse the family, not split it."""
        r = Registry()
        c1 = r.counter("dup_total", "a")
        c1.inc()
        c2 = r.counter("dup_total", "a")
        c2.inc()
        assert c1 is c2
        assert r.render().count("# TYPE dup_total counter") == 1
        assert "dup_total 2" in r.render()
        with __import__("pytest").raises(ValueError):
            r.histogram("dup_total", "clash")

    def test_registry_render(self):
        r = Registry()
        c = r.counter("a_total", "a")
        c.inc()
        h = r.histogram("b_seconds", "b", buckets=(1.0,))
        h.observe(0.5)
        text = r.render()
        assert text.endswith("\n")
        assert "a_total 1" in text and "b_seconds_count 1" in text
