"""Two-tower retrieval model (flax + optax), data-parallel over the mesh.

The deep-retrieval target of BASELINE.json (config 5) — not present in
the reference (SURVEY.md §2c lists it as the new-framework extension):
user and item ID-embedding towers with MLP heads, trained with in-batch
sampled-softmax contrastive loss. TPU mapping: batches are sharded over
the ``data`` mesh axis (XLA inserts the gradient all-reduce), embeddings
and MLP weights replicated; serving scores a user embedding against the
full item-embedding table with one MXU matmul + top_k.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


@dataclass
class TwoTowerParams:
    embed_dim: int = 32
    hidden: List[int] = field(default_factory=lambda: [64])
    out_dim: int = 32
    batch_size: int = 1024
    epochs: int = 5
    learning_rate: float = 0.01
    temperature: float = 0.1
    seed: int = 0
    # mid-train checkpoint/resume (SURVEY.md §5): save full state every
    # N epochs; a restarted train with the same dir resumes at the
    # newest epoch. None disables.
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 1
    # streaming path: total pair count from the reader's vocabulary
    # pass (avoids an extra counting pass over the event log)
    n_pairs: int = 0


def _towers(n_users: int, n_items: int, p: TwoTowerParams):
    import flax.linen as nn

    class Tower(nn.Module):
        vocab: int
        p: TwoTowerParams

        @nn.compact
        def __call__(self, ids):
            x = nn.Embed(self.vocab, self.p.embed_dim,
                         embedding_init=nn.initializers.normal(0.05))(ids)
            for h in self.p.hidden:
                x = nn.relu(nn.Dense(h)(x))
            x = nn.Dense(self.p.out_dim)(x)
            # L2-normalized embeddings → cosine retrieval
            return x / (np.float32(1e-8) + jnp_norm(x))

    def jnp_norm(x):
        import jax.numpy as jnp

        return jnp.linalg.norm(x, axis=-1, keepdims=True)

    return Tower(n_users, p), Tower(n_items, p)


@functools.lru_cache(maxsize=8)
def _compiled_train_epoch(n_users: int, n_items: int, embed_dim: int,
                          hidden: Tuple[int, ...], out_dim: int):
    """Geometry-keyed training program. ``learning_rate`` rides INSIDE
    the optimizer state (``optax.inject_hyperparams``) and
    ``temperature`` is a traced scalar argument, so eval-grid
    candidates differing only in those share one executable — and
    repeated train calls at one geometry stop re-tracing (the previous
    per-call ``@jax.jit`` closure compiled every call).

    Returns ``(user_tower, item_tower, opt, train_epoch)`` with
    ``train_epoch(variables, opt_state, users_e, items_e, temperature)``.
    """
    import jax
    import jax.numpy as jnp
    import optax

    geom = TwoTowerParams(embed_dim=embed_dim, hidden=list(hidden),
                          out_dim=out_dim)
    user_tower, item_tower = _towers(n_users, n_items, geom)
    # the init value is a placeholder: the caller sets
    # opt_state.hyperparams["learning_rate"] per candidate
    opt = optax.inject_hyperparams(optax.adam)(learning_rate=1e-3)

    def loss_fn(variables, bu, bi, temperature):
        uvv, ivv = variables
        ue = user_tower.apply(uvv, bu)          # (B, D)
        ie = item_tower.apply(ivv, bi)          # (B, D)
        logits = (ue @ ie.T) / temperature      # in-batch negatives
        labels = jnp.arange(bu.shape[0])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    @jax.jit
    def train_epoch(variables, opt_state, users_e, items_e, temperature):
        def step(carry, batch):
            variables, opt_state = carry
            bu, bi = batch
            loss, grads = jax.value_and_grad(loss_fn)(
                variables, bu, bi, temperature)
            updates, opt_state = opt.update(grads, opt_state)
            variables = optax.apply_updates(variables, updates)
            return (variables, opt_state), loss

        (variables, opt_state), losses = jax.lax.scan(
            step, (variables, opt_state), (users_e, items_e))
        return variables, opt_state, losses.mean()

    return user_tower, item_tower, opt, train_epoch


def two_tower_train(
    user_idx: np.ndarray, item_idx: np.ndarray,
    n_users: int, n_items: int,
    params: TwoTowerParams, mesh=None,
    pair_chunks: Optional[Any] = None,
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Train on positive (user, item) pairs; returns (user_variables,
    item_variables) flax param pytrees (host numpy).

    ``pair_chunks`` (a zero-arg callable returning an iterator of
    (user_idx, item_idx, …) numpy chunks, e.g.
    ``InteractionData.chunks``) selects the STREAMING input path: each
    epoch re-streams the chunks through a
    :class:`~predictionio_tpu.data.pipeline.DevicePrefetcher`
    (double-buffered host→HBM) and shuffles WITHIN chunks — event logs
    larger than host RAM train, at the cost of chunk-local instead of
    global shuffling (the standard streaming trade-off; pass the whole
    dataset as one chunk to recover exact global-shuffle semantics).
    Sub-batch remainders carry into the next chunk. ``user_idx``/
    ``item_idx`` may then be empty; the pair count comes from
    ``params.n_pairs`` (the reader's vocabulary pass) or, failing that,
    one extra counting pass."""
    import jax
    import jax.numpy as jnp

    p = params
    user_tower, item_tower, opt, epoch_fn = _compiled_train_epoch(
        n_users, n_items, p.embed_dim, tuple(p.hidden), p.out_dim)
    rng = jax.random.PRNGKey(p.seed)
    ru, ri = jax.random.split(rng)
    uv = user_tower.init(ru, jnp.zeros((1,), jnp.int32))
    iv = item_tower.init(ri, jnp.zeros((1,), jnp.int32))
    temperature = jnp.float32(p.temperature)

    def train_epoch(variables, opt_state, users_e, items_e):
        return epoch_fn(variables, opt_state, users_e, items_e,
                        temperature)

    n = len(user_idx)
    if pair_chunks is not None and n == 0:
        if p.n_pairs:
            n = p.n_pairs  # caller already counted (vocabulary pass)
        else:
            n = sum(len(c[0]) for c in pair_chunks())
    if n < 2:
        raise ValueError("two-tower training needs at least 2 positive pairs "
                         "(in-batch negatives)")
    n_dev = 1
    if mesh is not None and int(np.prod(mesh.devices.shape)) > 1:
        n_dev = int(np.prod(mesh.devices.shape))
    B = min(p.batch_size, n)
    if n_dev > 1:
        # batch axis is sharded over the mesh → must divide evenly
        B = max(n_dev, (B // n_dev) * n_dev)
        if B > n:  # too few pairs to fill one sharded batch → run unsharded
            n_dev = 1
            B = min(p.batch_size, n)
    n_batches = max(1, n // B)
    variables = (uv, iv)
    opt_state = opt.init(variables)
    # the candidate's learning rate enters THROUGH the optimizer state
    # (a traced leaf), not the compiled program
    opt_state.hyperparams["learning_rate"] = jnp.float32(p.learning_rate)

    if n_dev > 1:
        from jax.sharding import NamedSharding, PartitionSpec

        batch_sharding = NamedSharding(mesh, PartitionSpec(None, "data"))
    else:
        batch_sharding = None

    # mid-train checkpoint/resume: per-epoch RNG is seeded by epoch index
    # so a resumed run replays the exact batch permutations a straight
    # run would have used
    start_epoch = 0
    ckpt = None
    if p.checkpoint_dir:
        from predictionio_tpu.utils.checkpoint import TrainCheckpointer

        ckpt = TrainCheckpointer(p.checkpoint_dir)
        if ckpt.latest_step() is not None:
            from predictionio_tpu.utils.checkpoint import (
                CheckpointGeometryError,
            )

            try:
                template = {"variables": variables, "opt_state": opt_state}
                state, latest = ckpt.restore_latest_compatible(template)
                variables, opt_state = state["variables"], state["opt_state"]
                start_epoch = latest
                # THIS run's learning rate wins over the checkpointed
                # one — a restart that lowers lr to anneal must not
                # silently train at the old rate (r4 review)
                opt_state.hyperparams["learning_rate"] = \
                    jnp.float32(p.learning_rate)
            except CheckpointGeometryError:
                # CONFIRMED stale (e.g. different tower geometry) →
                # fresh start; wipe so the stale latest_step can't
                # shadow this run's saves. Transient read errors
                # propagate — wiping would destroy valid checkpoints.
                import warnings

                warnings.warn(
                    "two_tower checkpoints are stale (geometry/format change) — wiped; training restarts from scratch",
                    RuntimeWarning)
                ckpt.clear()

    last_loss = None
    for epoch in range(start_epoch, p.epochs):
        if pair_chunks is not None:
            # streaming path (SURVEY §2d C4): shuffle within each chunk,
            # reshape to scan batches, and let the prefetcher decode +
            # device_put the NEXT chunk while this one trains
            from predictionio_tpu.data.pipeline import DevicePrefetcher

            erng = np.random.default_rng(p.seed + epoch)

            # fixed-size (G, B) step groups: one dispatch and one
            # device_put per G steps, so the depth-2 prefetcher buffers
            # ~2·G steps of work and chunk decode genuinely overlaps
            # compute (per-(1, B)-step yields shrank the window to ~2
            # sub-millisecond steps — the device stalled at every chunk
            # boundary). Remainders carry across chunks; the tail that
            # can't fill a group trains as (1, B) steps — exactly TWO
            # compiled shapes regardless of chunk geometry.
            G = max(1, 65536 // B)

            def host_batches():
                carry_u = np.zeros(0, np.int32)
                carry_i = np.zeros(0, np.int32)
                for chunk in pair_chunks():
                    u_c = np.concatenate([carry_u, np.asarray(chunk[0],
                                                              np.int32)])
                    i_c = np.concatenate([carry_i, np.asarray(chunk[1],
                                                              np.int32)])
                    g = len(u_c) // (G * B)
                    if g == 0:
                        carry_u, carry_i = u_c, i_c
                        continue
                    cperm = erng.permutation(len(u_c))
                    take, rest = cperm[: g * G * B], cperm[g * G * B:]
                    carry_u, carry_i = u_c[rest], i_c[rest]
                    ub = u_c[take].reshape(g, G, B)
                    ib = i_c[take].reshape(g, G, B)
                    for j in range(g):
                        yield ub[j], ib[j]
                m = len(carry_u) // B
                if m:
                    cperm = erng.permutation(len(carry_u))[: m * B]
                    ub = carry_u[cperm].reshape(m, B)
                    ib = carry_i[cperm].reshape(m, B)
                    for j in range(m):
                        yield ub[j:j + 1], ib[j:j + 1]

            steps = 0
            with DevicePrefetcher(host_batches(),
                                  sharding=batch_sharding) as pf:
                for ue, ie in pf:
                    variables, opt_state, last_loss = train_epoch(
                        variables, opt_state, ue, ie)
                    steps += int(ue.shape[0])
            if steps == 0:
                raise ValueError(
                    f"streaming train performed zero steps: {n} pairs "
                    f"never filled one batch of {B}; lower batch_size")
        else:
            perm = np.random.default_rng(p.seed + epoch).permutation(n)[: n_batches * B]
            ue = user_idx[perm].reshape(n_batches, B).astype(np.int32)
            ie = item_idx[perm].reshape(n_batches, B).astype(np.int32)
            if batch_sharding is not None:
                ue = jax.device_put(ue, batch_sharding)
                ie = jax.device_put(ie, batch_sharding)
            variables, opt_state, last_loss = train_epoch(
                variables, opt_state, jnp.asarray(ue), jnp.asarray(ie))
        if ckpt is not None and (epoch + 1) % max(1, p.checkpoint_every) == 0:
            ckpt.save(epoch + 1, {"variables": jax.tree.map(np.asarray, variables),
                                  "opt_state": jax.tree.map(np.asarray, opt_state)})
    if ckpt is not None:
        ckpt.close()
    uvv, ivv = variables
    return (jax.tree.map(np.asarray, uvv), jax.tree.map(np.asarray, ivv))


def _tower_forward_np(variables, ids: np.ndarray) -> np.ndarray:
    """Numpy replay of the tower forward pass (Embed → Dense+relu… → Dense
    → L2 normalize). Serving stays off the accelerator: a per-query tower
    pass is a handful of tiny GEMVs — host numpy beats a device dispatch
    on p50 and keeps serving alive when no accelerator is attached."""
    p = variables["params"]
    x = np.asarray(p["Embed_0"]["embedding"])[ids]
    dense_names = sorted((k for k in p if k.startswith("Dense_")),
                         key=lambda k: int(k.split("_")[1]))
    for j, name in enumerate(dense_names):
        x = x @ np.asarray(p[name]["kernel"]) + np.asarray(p[name]["bias"])
        if j < len(dense_names) - 1:
            x = np.maximum(x, 0.0)
    return x / (1e-8 + np.linalg.norm(x, axis=-1, keepdims=True))


def two_tower_embed_items(item_variables, n_items: int,
                          params: TwoTowerParams) -> np.ndarray:
    """Precompute the full item-embedding table for serving."""
    return _tower_forward_np(item_variables, np.arange(n_items))


def two_tower_user_embed(user_variables, user_id: int, n_users: int,
                         params: TwoTowerParams) -> np.ndarray:
    return _tower_forward_np(user_variables, np.asarray([user_id]))[0]


def two_tower_embed_users(user_variables, n_users: int,
                          params: TwoTowerParams,
                          chunk: int = 65536) -> np.ndarray:
    """Precompute every user's embedding (r5). With both tables
    materialized, two-tower serving rides the SAME device-resident
    gather→score→top-k program as ALS (`models/als.ResidentScorer`) —
    one dispatch per (micro-)batch instead of a host matvec per query.
    Chunked so the intermediate activations stay bounded."""
    return np.concatenate([
        _tower_forward_np(user_variables, np.arange(lo, min(lo + chunk,
                                                            n_users)))
        for lo in range(0, n_users, chunk)])


def two_tower_build_index(item_embeds: np.ndarray, m: int = 8, k: int = 256,
                          *, iters: int = 8, seed: int = 0,
                          sample: int = 65536, opq: bool = False,
                          opq_iters: int = 4, shards: int = 0):
    """Build the PQ retrieval index over the materialized item table
    (ROADMAP item 3) — the `pio train`-time step that turns exact
    top-k serving into ADC-shortlist + re-rank at 10M+ corpora. Thin
    model-layer wrapper so templates depend on models/, not on the
    index internals; returns a :class:`predictionio_tpu.ann.PQIndex`
    (persisted inside the model artifact by the template's
    ``save_model``).

    ``opq=True`` trains an OPQ-style learned rotation before
    quantization (versioned into the blob); ``shards > 1`` records the
    intended serving-mesh width as a build hint that
    ``maybe_ann_scorer`` picks up at deploy time."""
    from predictionio_tpu import ann

    return ann.build_index(np.asarray(item_embeds, np.float32), m, k,
                           iters=iters, seed=seed, sample=sample,
                           opq=opq, opq_iters=opq_iters,
                           shards=(int(shards) if shards
                                   and int(shards) > 1 else None))
