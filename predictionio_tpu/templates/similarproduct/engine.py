"""Similar Product template: item-to-item similarity from ALS factors.

Behavioral equivalent of the reference's similar-product template
(reference: [U] examples/scala-parallel-similarproduct/ — "view" events
→ implicit ALS; query = list of liked items → top-K cosine-similar
items, with category/whitelist/blacklist filters; SURVEY.md §2c).

    POST /queries.json {"items": ["i1", "i3"], "num": 4,
                        "categories": ["c1"], "blackList": ["i5"]}
    → {"itemScores": [{"item": "i2", "score": 0.87}, ...]}
"""

from __future__ import annotations

import io
import pickle
from collections import Counter
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    AverageMetric,
    DataSource,
    Engine,
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    FirstServing,
    IdentityPreparator,
    WorkflowContext,
)
from predictionio_tpu.data import store as event_store
from predictionio_tpu.models.als import (
    ALSParams,
    RatingsCOO,
    als_train,
    similar_items,
)
from predictionio_tpu.utils.bimap import BiMap


@dataclass
class DataSourceParams:
    app_name: str = ""
    event_names: List[str] = field(default_factory=lambda: ["view"])


@dataclass
class TrainingData:
    views: List[tuple]             # (user, item) pairs
    item_categories: Dict[str, List[str]]  # from $set item properties


class SimilarProductDataSource(DataSource):
    ParamsClass = DataSourceParams

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        p: DataSourceParams = self.params
        views = [
            (e.entity_id, e.target_entity_id)
            for e in event_store.find(
                p.app_name, entity_type="user", target_entity_type="item",
                event_names=p.event_names, storage=ctx.storage)
            if e.target_entity_id is not None
        ]
        if not views:
            raise ValueError("no view events found; import events before training")
        cats = {
            entity_id: list(props.get("categories") or [])
            for entity_id, props in event_store.aggregate_properties(
                p.app_name, "item", storage=ctx.storage).items()
        }
        return TrainingData(views, cats)

    def read_eval(self, ctx: WorkflowContext):
        """Item-to-item retrieval protocol: each user's LAST viewed
        item is held out; the query carries the user's remaining items
        and the held-out one must rank in the top-k similars."""
        td = self.read_training(ctx)
        last = {}
        cnt = {}
        for idx, (u, _i) in enumerate(td.views):
            last[u] = idx
            cnt[u] = cnt.get(u, 0) + 1
        held = sorted(idx for u, idx in last.items() if cnt[u] >= 3)
        if not held:
            raise ValueError("no user has >= 3 views to hold one out")
        held_set = set(held)
        keep = [pr for idx, pr in enumerate(td.views)
                if idx not in held_set]
        by_user = {}
        for u, i in keep:
            by_user.setdefault(u, []).append(i)
        qa = [({"items": by_user[td.views[idx][0]], "num": 10},
               td.views[idx][1]) for idx in held]
        return [(TrainingData(keep, td.item_categories), {"fold": 0}, qa)]


@dataclass
class ALSAlgorithmParams:
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: Optional[int] = None


class SimilarProductModel:
    def __init__(self, V: np.ndarray, item_ids: BiMap,
                 item_categories: Dict[str, List[str]]) -> None:
        self.V = V
        self.item_ids = item_ids
        self._inv = item_ids.inverse()
        self.item_categories = item_categories

    def query(self, items: List[str], num: int,
              categories: Optional[List[str]] = None,
              white_list: Optional[List[str]] = None,
              black_list: Optional[List[str]] = None) -> List[Dict[str, Any]]:
        idxs = np.asarray([self.item_ids[i] for i in items
                           if i in self.item_ids], np.int32)
        if idxs.size == 0:
            return []
        # over-fetch so post-filters still fill `num`
        top, scores = similar_items(self.V, idxs, min(len(self.item_ids),
                                                      num + idxs.size + 50))
        cats = set(categories or [])
        white = set(white_list or [])
        black = set(black_list or [])
        out = []
        for i, s in zip(top, scores):
            item = self._inv[int(i)]
            if white and item not in white:
                continue
            if item in black:
                continue
            if cats and not cats.intersection(self.item_categories.get(item, [])):
                continue
            out.append({"item": item, "score": float(s)})
            if len(out) >= num:
                break
        return out


class ALSAlgorithm(Algorithm):
    ParamsClass = ALSAlgorithmParams

    def sanity_check(self, data: TrainingData) -> None:
        if not data.views:
            raise ValueError("empty view data")

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> SimilarProductModel:
        p: ALSAlgorithmParams = self.params
        user_ids = BiMap.string_int(u for u, _ in pd.views)
        item_ids = BiMap.string_int(i for _, i in pd.views)
        counts = Counter((user_ids[u], item_ids[i]) for u, i in pd.views)
        uu = np.fromiter((k[0] for k in counts), np.int32, len(counts))
        ii = np.fromiter((k[1] for k in counts), np.int32, len(counts))
        vv = np.fromiter(counts.values(), np.float32, len(counts))
        coo = RatingsCOO(uu, ii, vv, len(user_ids), len(item_ids))
        _, V = als_train(
            coo,
            ALSParams(rank=p.rank, iterations=p.num_iterations, reg=p.lambda_,
                      implicit=True, alpha=p.alpha,
                      seed=0 if p.seed is None else p.seed),
            mesh=ctx.mesh)
        return SimilarProductModel(V, item_ids, pd.item_categories)

    def predict(self, model: SimilarProductModel, query: Dict[str, Any]) -> Dict[str, Any]:
        return {"itemScores": model.query(
            [str(i) for i in query.get("items", [])],
            int(query.get("num", 10)),
            query.get("categories"),
            query.get("whiteList"),
            query.get("blackList"),
        )}

    def save_model(self, model: SimilarProductModel, instance_dir: Optional[str]) -> bytes:
        buf = io.BytesIO()
        np.savez_compressed(buf, V=model.V)
        return pickle.dumps({
            "npz": buf.getvalue(),
            "item_ids": model.item_ids.to_dict(),
            "cats": model.item_categories,
        })

    def load_model(self, blob: Optional[bytes], instance_dir: Optional[str]) -> SimilarProductModel:
        assert blob is not None
        d = pickle.loads(blob)
        arrs = np.load(io.BytesIO(d["npz"]))
        return SimilarProductModel(arrs["V"], BiMap(d["item_ids"]), d["cats"])


def engine_factory() -> Engine:
    return Engine(
        data_source_cls=SimilarProductDataSource,
        preparator_cls=IdentityPreparator,
        algorithm_cls_map={"als": ALSAlgorithm},
        serving_cls=FirstServing,
    )


# -- evaluation (pio eval out of the box) -------------------------------------


class HitRateAtK(AverageMetric):
    def __init__(self, k: int = 10) -> None:
        self.k = k

    def calculate_one(self, query, predicted, actual) -> float:
        items = [s["item"] for s in predicted.get("itemScores", [])][: self.k]
        return 1.0 if actual in items else 0.0

    @property
    def header(self) -> str:
        return f"HitRate@{self.k}"


class SPEvaluation(Evaluation):
    engine_factory = staticmethod(engine_factory)
    metric = HitRateAtK(10)


class DefaultGrid(EngineParamsGenerator):
    """Rank candidates; app via $PIO_EVAL_APP_NAME."""

    @property
    def engine_params_list(self):
        import os

        app = os.environ.get("PIO_EVAL_APP_NAME", "MyApp1")
        return [EngineParams(
            data_source_params=DataSourceParams(app_name=app),
            algorithms_params=[("als", ALSAlgorithmParams(rank=r))])
            for r in (8, 16)]
