"""Shared integrity primitives: digests, verdict counters, refusal.

Every checksummed artifact above the native event log (snapshot
columns, model blobs) uses the same discipline — SHA-256 digest
written beside the data, verified on every load — and reports through
the same three counters, so one ``/metrics`` scrape answers "is
anything corrupt?" across the whole storage stack:

- ``pio_integrity_verified_total{artifact}`` — reads whose checksum
  matched;
- ``pio_integrity_failed_total{artifact}``   — reads refused (or, for
  the cache-shaped snapshot artifact, rebuilt) on mismatch;
- ``pio_quarantined_total{artifact}``        — corrupt byte ranges
  preserved in a quarantine sidecar instead of silently dropped.

``artifact`` is one of ``eventlog`` / ``snapshot`` / ``model``.

The eventlog's own per-record CRC32C lives in the native engine
(eventlog.cc) and the pure-Python scanner
(:mod:`predictionio_tpu.data.pel_integrity`); this module covers the
Python-side blobs where a cryptographic digest is cheap relative to
the artifact size and removes any collision question.
"""

from __future__ import annotations

import hashlib
from typing import Optional

from predictionio_tpu.utils.metrics import REGISTRY

#: filename suffix for digest sidecars (``model.bin`` -> ``model.bin.sha256``)
DIGEST_SUFFIX = ".sha256"

INTEGRITY_VERIFIED = REGISTRY.counter(
    "pio_integrity_verified_total",
    "Artifact reads whose checksum verified", ("artifact",))
INTEGRITY_FAILED = REGISTRY.counter(
    "pio_integrity_failed_total",
    "Artifact reads refused or rebuilt on checksum mismatch",
    ("artifact",))
QUARANTINED = REGISTRY.counter(
    "pio_quarantined_total",
    "Corrupt byte ranges preserved in quarantine sidecars", ("artifact",))


class IntegrityError(RuntimeError):
    """A checksummed artifact failed verification — the read is
    REFUSED, never served. Deliberately not an ``IOError``: retry
    logic must not treat bad bytes as a transient fault."""


def sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def verify_blob(blob: bytes, expected_hex: Optional[str],
                artifact: str, what: str = "") -> None:
    """Verify ``blob`` against a hex digest, counting the verdict.

    ``expected_hex`` of None means "no sidecar" (an artifact written
    before checksums existed): accepted without a verdict so old
    deployments keep working — ``pio fsck`` reports these as
    ``unchecksummed``.
    """
    if expected_hex is None:
        return
    actual = sha256_hex(blob)
    if actual != expected_hex.strip():
        INTEGRITY_FAILED.inc((artifact,))
        raise IntegrityError(
            f"{artifact} checksum mismatch{f' for {what}' if what else ''}: "
            f"expected {expected_hex.strip()[:16]}…, got {actual[:16]}… "
            f"({len(blob)} bytes) — refusing to serve corrupt data")
    INTEGRITY_VERIFIED.inc((artifact,))
