from predictionio_tpu.core.workflow import (
    run_train,
    run_evaluation,
    prepare_deploy,
    DeployedEngine,
)

__all__ = ["run_train", "run_evaluation", "prepare_deploy", "DeployedEngine"]
