"""Recommendation template: ALS collaborative filtering.

Behavioral equivalent of the reference's quickstart template
(reference: [U] examples/scala-parallel-recommendation/ — DataSource
reads "rate"/"buy" events into Ratings, ALSAlgorithm wraps MLlib
``ALS.train`` into an ALSModel with user/item BiMaps, Serving = first;
SURVEY.md §2c). Query/response wire shapes match the reference:

    POST /queries.json  {"user": "1", "num": 4}
    → {"itemScores": [{"item": "22", "score": 4.5}, ...]}

The compute is :mod:`predictionio_tpu.models.als` (JAX, mesh-aware).
"""

from __future__ import annotations

import io
import pickle
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    FirstServing,
    Metric,
    Preparator,
    WorkflowContext,
)
from predictionio_tpu.data import store as event_store
from predictionio_tpu.data.cleaning import SelfCleaningDataSource
from predictionio_tpu.models.als import (
    ALSParams,
    RatingsCOO,
    als_train,
    recommend,
)
from predictionio_tpu.utils.bimap import BiMap


@dataclass
class Rating:
    user: str
    item: str
    rating: float


@dataclass
class TrainingData:
    ratings: List[Rating]


@dataclass
class DataSourceParams:
    app_name: str = ""
    event_names: List[str] = field(default_factory=lambda: ["rate", "buy"])
    # rating assigned to implicit "buy" events (reference quickstart: 4.0)
    buy_rating: float = 4.0
    eval_k: int = 0          # >0 enables read_eval with k folds
    eval_seed: int = 3
    #: optional {"duration": "30 days", "removeDuplicates": bool,
    #: "compressProperties": bool} — SelfCleaningDataSource window
    event_window: Optional[Dict[str, Any]] = None


class RecDataSource(SelfCleaningDataSource, DataSource):
    ParamsClass = DataSourceParams

    def _read_ratings(self, ctx: WorkflowContext) -> List[Rating]:
        p: DataSourceParams = self.params
        out: List[Rating] = []
        for e in event_store.find(
            p.app_name,
            entity_type="user",
            target_entity_type="item",
            event_names=p.event_names,
            storage=ctx.storage,
        ):
            if e.event == "rate":
                try:
                    r = float(e.properties["rating"])
                except (KeyError, TypeError, ValueError):
                    continue
            else:  # implicit positive event ("buy")
                r = p.buy_rating
            assert e.target_entity_id is not None
            out.append(Rating(e.entity_id, e.target_entity_id, r))
        return out

    def read_training(self, ctx: WorkflowContext) -> TrainingData:
        self.clean(ctx, self.params.app_name)
        ratings = self._read_ratings(ctx)
        if not ratings:
            raise ValueError(
                "no rate/buy events found; import events before `pio train`")
        return TrainingData(ratings)

    def read_eval(self, ctx: WorkflowContext):
        p: DataSourceParams = self.params
        if p.eval_k <= 0:
            raise ValueError("set dataSourceParams.evalK > 0 to evaluate")
        ratings = self._read_ratings(ctx)
        rng = np.random.default_rng(p.eval_seed)
        fold_of = rng.integers(0, p.eval_k, size=len(ratings))
        folds = []
        for f in range(p.eval_k):
            train = TrainingData([r for r, g in zip(ratings, fold_of) if g != f])
            test = [r for r, g in zip(ratings, fold_of) if g == f]
            qa = [({"user": r.user, "item": r.item, "num": 1}, r.rating) for r in test]
            folds.append((train, {"fold": f}, qa))
        return folds


class RecPreparator(Preparator):
    """Pass-through (reference quickstart Preparator)."""

    def prepare(self, ctx: WorkflowContext, training_data: TrainingData) -> TrainingData:
        return training_data


@dataclass
class ALSAlgorithmParams:
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    seed: Optional[int] = None
    implicit_prefs: bool = False
    alpha: float = 1.0
    # mid-train checkpoint cadence (iterations per block) when the
    # workflow provides a checkpoint dir; 0 disables (SURVEY.md §5)
    checkpoint_every: int = 5
    # bf16 factor gathers: ~half the training HBM traffic for ~1e-2
    # relative factor error (see models/als.py ALSParams.bf16_gather)
    bf16_gather: bool = False


class ALSModel:
    """Resident serving model: factor matrices + id↔index BiMaps."""

    def __init__(self, U: np.ndarray, V: np.ndarray,
                 user_ids: BiMap, item_ids: BiMap) -> None:
        self.U = U
        self.V = V
        self.user_ids = user_ids
        self.item_ids = item_ids
        self._item_inv = item_ids.inverse()

    def recommend_products(self, user: str, num: int) -> List[Dict[str, Any]]:
        uidx = self.user_ids.get(user)
        if uidx is None:
            return []
        top, scores = recommend(self.U, self.V, uidx, num)
        return [
            {"item": self._item_inv[int(i)], "score": float(s)}
            for i, s in zip(top, scores)
        ]

    def predict_rating(self, user: str, item: str) -> Optional[float]:
        uidx = self.user_ids.get(user)
        iidx = self.item_ids.get(item)
        if uidx is None or iidx is None:
            return None
        return float(self.U[uidx] @ self.V[iidx])


class ALSAlgorithm(Algorithm):
    ParamsClass = ALSAlgorithmParams

    def sanity_check(self, data: TrainingData) -> None:
        if not data.ratings:
            raise ValueError("empty TrainingData.ratings")

    @staticmethod
    def _to_coo(pd: TrainingData):
        user_ids = BiMap.string_int(r.user for r in pd.ratings)
        item_ids = BiMap.string_int(r.item for r in pd.ratings)
        coo = RatingsCOO(
            user_idx=np.fromiter((user_ids[r.user] for r in pd.ratings),
                                 np.int32, len(pd.ratings)),
            item_idx=np.fromiter((item_ids[r.item] for r in pd.ratings),
                                 np.int32, len(pd.ratings)),
            rating=np.fromiter((r.rating for r in pd.ratings),
                               np.float32, len(pd.ratings)),
            n_users=len(user_ids),
            n_items=len(item_ids),
        )
        return coo, user_ids, item_ids

    @staticmethod
    def _als_params(p: ALSAlgorithmParams) -> ALSParams:
        return ALSParams(
            rank=p.rank, iterations=p.num_iterations, reg=p.lambda_,
            implicit=p.implicit_prefs, alpha=p.alpha,
            seed=0 if p.seed is None else p.seed,
            bf16_gather=p.bf16_gather,
        )

    @classmethod
    def train_many(cls, ctx: WorkflowContext, pd: TrainingData,
                   params_list) -> List[ALSModel]:
        """Grid fan-out (`pio eval`): the id maps + bucketed layout
        build once, and candidates differing only in lambda/alpha share
        one compiled executable (reg/alpha are traced scalars — see
        models/als.als_train_many). SURVEY.md §2d P4."""
        from predictionio_tpu.models.als import als_train_many

        coo, user_ids, item_ids = cls._to_coo(pd)
        results = als_train_many(
            coo, [cls._als_params(p) for p in params_list], mesh=ctx.mesh)
        return [ALSModel(U, V, user_ids, item_ids) for U, V in results]

    def train(self, ctx: WorkflowContext, pd: TrainingData) -> ALSModel:
        p: ALSAlgorithmParams = self.params
        coo, user_ids, item_ids = self._to_coo(pd)
        U, V = als_train(
            coo,
            self._als_params(p),
            mesh=ctx.mesh,
            # restart-from-checkpoint (run_train --resume): save V every
            # checkpoint_every iterations under the workflow's ckpt dir
            checkpointer=ctx.checkpointer("als"),
            checkpoint_every=p.checkpoint_every,
        )
        return ALSModel(U, V, user_ids, item_ids)

    def predict(self, model: ALSModel, query: Dict[str, Any]) -> Dict[str, Any]:
        user = str(query["user"])
        if "item" in query:  # rating-prediction shape (used by evaluation)
            r = model.predict_rating(user, str(query["item"]))
            return {"itemScores": (
                [{"item": str(query["item"]), "score": r}] if r is not None else [])}
        num = int(query.get("num", 10))
        return {"itemScores": model.recommend_products(user, num)}

    # structured persistence: npz for factors (compact, zero-copy load)
    def save_model(self, model: ALSModel, instance_dir: Optional[str]) -> bytes:
        buf = io.BytesIO()
        np.savez_compressed(buf, U=model.U, V=model.V)
        return pickle.dumps({
            "npz": buf.getvalue(),
            "user_ids": model.user_ids.to_dict(),
            "item_ids": model.item_ids.to_dict(),
        })

    def load_model(self, blob: Optional[bytes], instance_dir: Optional[str]) -> ALSModel:
        assert blob is not None
        d = pickle.loads(blob)
        arrs = np.load(io.BytesIO(d["npz"]))
        return ALSModel(arrs["U"], arrs["V"],
                        BiMap(d["user_ids"]), BiMap(d["item_ids"]))


def engine_factory() -> Engine:
    return Engine(
        data_source_cls=RecDataSource,
        preparator_cls=RecPreparator,
        algorithm_cls_map={"als": ALSAlgorithm},
        serving_cls=FirstServing,
    )


# -- evaluation (pio eval out of the box) -------------------------------------


class NegRMSE(Metric):
    """-RMSE of predicted vs held-out ratings over the eval folds
    (higher is better, so the evaluator's argmax picks the lowest
    error). Cold (user, item) pairs — unknown to the trained fold —
    are skipped, the OptionAverageMetric convention."""

    higher_is_better = True

    def calculate(self, ctx, eval_data):
        import math

        errs = []
        for _, qpa in eval_data:
            for q, p, a in qpa:
                scores = p.get("itemScores", [])
                if scores and scores[0].get("score") is not None:
                    errs.append((float(scores[0]["score"]) - float(a)) ** 2)
        return (-math.sqrt(sum(errs) / len(errs)) if errs
                else float("nan"))

    @property
    def header(self) -> str:
        return "NegRMSE"


class RecEvaluation(Evaluation):
    engine_factory = staticmethod(engine_factory)
    metric = NegRMSE()


class DefaultGrid(EngineParamsGenerator):
    """Rank/λ candidates over 2 folds; app via $PIO_EVAL_APP_NAME."""

    @property
    def engine_params_list(self):
        import os

        app = os.environ.get("PIO_EVAL_APP_NAME", "MyApp1")
        return [EngineParams(
            data_source_params=DataSourceParams(app_name=app, eval_k=2),
            algorithms_params=[("als", ALSAlgorithmParams(
                rank=r, num_iterations=8, lambda_=lam, seed=3))])
            for r in (8, 16) for lam in (0.01, 0.1)]
